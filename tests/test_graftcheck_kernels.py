"""graftcheck --kernels suite: K001–K005 on one-violation fixture
twins, the DMA walker's path semantics, the interpret-mode VMEM sweep
(accountant bounds, alignment, family coverage), the artifact gate,
the repo gate under the committed baseline, the non-vacuity floors,
and the CLI/queue kernelcheck contract."""
import json
import logging
import os
import re
import sys

import pytest
from graftcheck_util import (REPO, check_suppression, check_twin,
                             fixture_mod as _mod, inject, run_cli, tmp_mod)

from raft_tpu.analysis import (kernel_stats, kernel_vmem_audit,
                               load_baseline, run_artifacts, run_kernels,
                               split_by_baseline)
from raft_tpu.analysis.kernels import (KERNEL_DRIFT_TOLERANCE, KERNEL_RULES,
                                       _numeric_alignment,
                                       _reset_kernel_warn,
                                       rule_carry_invariance,
                                       rule_dma_pairing,
                                       rule_interpret_divergence,
                                       rule_tile_alignment,
                                       rule_vmem_accounting)

RULES = {"K001": rule_dma_pairing, "K002": rule_vmem_accounting,
         "K003": rule_tile_alignment, "K004": rule_interpret_divergence,
         "K005": rule_carry_invariance}

_PALLAS_HEADER = (
    "from jax.experimental import pallas as pl  # noqa: F401\n"
    "from jax.experimental.pallas import tpu as pltpu\n\n\n")


# ------------------------------------------------------------ K-rule twins

@pytest.mark.parametrize("rule_id,stem,expect_qual", [
    ("K001", "k001", "leaky_kernel"),
    ("K002", "k002", "doubled"),
    ("K003", "k003", "_acc_kernel"),
    ("K004", "k004", "dispatch"),
    ("K005", "k005", "scan_rows"),
], ids=list(RULES))
def test_rule_flags_bad_and_passes_clean(rule_id, stem, expect_qual):
    check_twin(RULES[rule_id], rule_id, stem, expect_qual)


def test_clean_twins_pass_every_kernel_rule():
    for stem in ("k001", "k002", "k003", "k004", "k005"):
        mod = _mod(f"{stem}_clean.py")
        for rule in KERNEL_RULES:
            assert rule(mod) == [], (stem, rule.__name__)


@pytest.mark.parametrize("rule_id,fname,anchor", [
    ("K001", "k001_bad.py", "cp.start()"),
    ("K002", "k002_bad.py", "return pl.pallas_call("),
    ("K003", "k003_bad.py",
     "out_specs=pl.BlockSpec((8, 128), lambda i, j: (i, 0)),"),
    ("K004", "k004_bad.py", "if interpret:"),
    ("K005", "k005_bad.py", "return (acc + x[i], best, i)"),
], ids=list(RULES))
def test_inline_suppression(tmp_path, rule_id, fname, anchor):
    check_suppression(RULES[rule_id], tmp_path, fname, anchor, rule_id)


# -------------------------------------------- K001 DMA walker semantics

def test_k001_double_start_without_wait(tmp_path):
    src = _PALLAS_HEADER + (
        "def kernel(a, b, sem):\n"
        "    cp = pltpu.make_async_copy(a, b, sem)\n"
        "    cp.start()\n"
        "    cp.start()\n"
        "    cp.wait()\n"
    )
    mod = tmp_mod(tmp_path, "double.py", src)
    found = rule_dma_pairing(mod)
    assert [(f.rule, f.qualname) for f in found] == [("K001", "kernel")]
    assert "started twice" in found[0].message


def test_k001_unbound_start_can_never_be_awaited(tmp_path):
    src = _PALLAS_HEADER + (
        "def kernel(a, b, sem):\n"
        "    pltpu.make_async_copy(a, b, sem).start()\n"
    )
    mod = tmp_mod(tmp_path, "unbound.py", src)
    found = rule_dma_pairing(mod)
    assert [(f.rule, f.qualname) for f in found] == [("K001", "kernel")]
    assert "unbound" in found[0].message


def test_k001_return_before_wait_is_an_exit_path(tmp_path):
    src = _PALLAS_HEADER + (
        "def kernel(a, b, sem, flag):\n"
        "    cp = pltpu.make_async_copy(a, b, sem)\n"
        "    cp.start()\n"
        "    if flag:\n"
        "        return 0\n"
        "    cp.wait()\n"
        "    return 1\n"
    )
    mod = tmp_mod(tmp_path, "early.py", src)
    found = rule_dma_pairing(mod)
    assert [(f.rule, f.qualname) for f in found] == [("K001", "kernel")]
    assert "no matching .wait()" in found[0].message


def test_k001_loop_body_start_without_wait_leaks(tmp_path):
    # one iteration starts a copy the next iteration's start clobbers
    src = _PALLAS_HEADER + (
        "def kernel(a, b, sem, rows):\n"
        "    for i in rows:\n"
        "        cp = pltpu.make_async_copy(a.at[i], b.at[i], sem)\n"
        "        cp.start()\n"
    )
    mod = tmp_mod(tmp_path, "loop.py", src)
    found = rule_dma_pairing(mod)
    assert [(f.rule, f.qualname) for f in found] == [("K001", "kernel")]


def test_k001_wait_only_descriptor_is_the_legal_idiom(tmp_path):
    src = _PALLAS_HEADER + (
        "def kernel(a, b, sem):\n"
        "    cp = pltpu.make_async_copy(a, b, sem)\n"
        "    cp.wait()\n"
    )
    assert rule_dma_pairing(tmp_mod(tmp_path, "waitonly.py", src)) == []


def test_k001_semaphore_imbalance(tmp_path):
    src = _PALLAS_HEADER + (
        "def kernel(left, right):\n"
        "    bar = pltpu.get_barrier_semaphore()\n"
        "    pltpu.semaphore_signal(bar, device_id=left)\n"
        "    pltpu.semaphore_signal(bar, device_id=right)\n"
        "    pltpu.semaphore_wait(bar, 3)\n"
    )
    mod = tmp_mod(tmp_path, "sem.py", src)
    found = rule_dma_pairing(mod)
    assert [(f.rule, f.qualname) for f in found] == [("K001", "kernel")]
    assert "2 signal(s) vs wait amount 3" in found[0].message


def test_k001_dynamic_wait_amount_is_not_statically_judged(tmp_path):
    src = _PALLAS_HEADER + (
        "def kernel(n):\n"
        "    bar = pltpu.get_barrier_semaphore()\n"
        "    pltpu.semaphore_signal(bar)\n"
        "    pltpu.semaphore_wait(bar, n)\n"
    )
    assert rule_dma_pairing(tmp_mod(tmp_path, "dyn.py", src)) == []


# ------------------------------------------------- K003/K004/K005 extras

def test_k003_literal_unaligned_block_dims(tmp_path):
    src = (
        "from jax.experimental import pallas as pl\n\n\n"
        "def plan(x):\n"
        "    return pl.BlockSpec((7, 100), lambda i: (i, 0))\n"
    )
    mod = tmp_mod(tmp_path, "unaligned.py", src)
    found = rule_tile_alignment(mod)
    assert [(f.rule, f.qualname) for f in found] == [("K003", "plan")]
    assert "lane dim 100" in found[0].message
    assert "sublane dim 7" in found[0].message


def test_k003_numeric_alignment_tolerates_subtile_dims():
    # (1, 96) is under one (8, 128) tile: Mosaic pads it — no finding;
    # (16, 640) is multi-tile and aligned; (24, 384) fine; (16, 200) bad
    assert _numeric_alignment([("in", (1, 96)), ("in", (16, 640)),
                               ("out", (24, 384))]) == []
    bad = _numeric_alignment([("in", (16, 200))])
    assert len(bad) == 1 and "lane dim 200" in bad[0]


def test_k004_passthrough_kwarg_is_not_a_divergence(tmp_path):
    src = (
        "from jax.experimental import pallas as pl  # noqa: F401\n\n\n"
        "def run(kernel_fn, interpret=False):\n"
        "    return kernel_fn(interpret=interpret)\n"
    )
    assert rule_interpret_divergence(
        tmp_mod(tmp_path, "pass.py", src)) == []


def test_k004_not_interpret_expression_is_flagged(tmp_path):
    src = (
        "from jax.experimental import pallas as pl  # noqa: F401\n\n\n"
        "def run(kernel_fn, interpret=False):\n"
        "    return kernel_fn(barrier=not interpret)\n"
    )
    found = rule_interpret_divergence(tmp_mod(tmp_path, "notkw.py", src))
    assert [(f.rule, f.qualname) for f in found] == [("K004", "run")]


def test_k005_lambda_body_arity_mismatch(tmp_path):
    src = (
        "import jax\n"
        "from jax.experimental import pallas as pl  # noqa: F401\n\n\n"
        "def drain(x):\n"
        "    return jax.lax.while_loop(\n"
        "        lambda c: c[0] < 4,\n"
        "        lambda c: (c[0] + 1, c[1], 0),\n"
        "        (0, x),\n"
        "    )\n"
    )
    found = rule_carry_invariance(tmp_mod(tmp_path, "lam.py", src))
    assert [(f.rule, f.qualname) for f in found] == [("K005", "drain")]
    assert "init carries 2" in found[0].message


def test_k005_starred_init_is_out_of_static_reach(tmp_path):
    src = (
        "import jax\n"
        "from jax.experimental import pallas as pl  # noqa: F401\n\n\n"
        "def step(x, carry):\n"
        "    return jax.lax.fori_loop(\n"
        "        0, 4, lambda i, c: (c[0], c[1], 0), (*carry, 0))\n"
    )
    assert rule_carry_invariance(tmp_mod(tmp_path, "star.py", src)) == []


# ----------------------------------------- the interpret-mode VMEM sweep

@pytest.fixture(scope="module")
def sweep():
    return kernel_vmem_audit()


def test_sweep_covers_every_family_at_three_shapes(sweep):
    results, _ = sweep
    by_family = {}
    for r in results:
        by_family.setdefault(r.family, []).append(r)
    assert set(by_family) == {"l2", "ivf", "pq", "cagra", "ring"}
    for family, rows in by_family.items():
        assert len(rows) >= 3, family


def test_sweep_is_clean_and_accountants_bound_the_live_set(sweep):
    results, findings = sweep
    assert findings == [], "\n".join(f.format() for f in findings)
    for r in results:
        assert r.ok, (r.family, r.point, r.note)
        if r.family == "ring":
            assert "2 DMA semaphores" in r.note
            continue
        # the crash direction: the committed accountant must bound the
        # captured block+scratch live set from above, within tolerance
        assert r.measured_bytes > 0, (r.family, r.point)
        assert r.accountant_bytes >= r.measured_bytes, (r.family, r.point)
        assert r.ratio <= KERNEL_DRIFT_TOLERANCE, (r.family, r.point,
                                                   r.ratio)


def test_sweep_tiles_come_from_the_captured_call(sweep):
    results, _ = sweep
    tiled = [r for r in results if r.family in ("l2", "ivf", "pq", "cagra")]
    for r in tiled:
        assert re.match(r"^(tm=\d+,tn=\d+|pad_tile=\d+|ct=\d+)$", r.tiles), \
            (r.family, r.tiles)


def test_sweep_warns_once_when_pallas_is_unavailable(monkeypatch, caplog):
    import jax.experimental
    _reset_kernel_warn()
    # both halves matter: `from jax.experimental import pallas` resolves
    # via getattr on the parent package when it can, and only falls back
    # to sys.modules when the attribute is gone
    monkeypatch.delattr(jax.experimental, "pallas", raising=False)
    monkeypatch.setitem(sys.modules, "jax.experimental.pallas", None)
    with caplog.at_level(logging.WARNING, "raft_tpu.analysis.kernels"):
        assert kernel_vmem_audit() == ([], [])
        assert kernel_vmem_audit() == ([], [])
    skips = [r for r in caplog.records if "sweep skipped" in r.message]
    assert len(skips) == 1  # warn-once
    _reset_kernel_warn()


# ------------------------------------------------------ the artifact gate

def test_artifacts_gate_is_clean_and_reports_the_stale_probe():
    findings, report = run_artifacts(REPO)
    assert findings == [], "\n".join(f.format() for f in findings)
    stale = [ln for ln in report if "STALE pre-v3" in ln]
    assert len(stale) == 1 and "PALLAS_PROBE_tpu.json" in stale[0]
    # the stale report must enumerate the unverified verdict families
    assert "cagra" in stale[0] and "ivf_pq" in stale[0]


def test_artifacts_gate_flags_a_loader_rejected_table(tmp_path):
    (tmp_path / "SELECT_K_TABLE_x.json").write_text(
        json.dumps({"platform": "x", "crossovers": []}))
    findings, _ = run_artifacts(str(tmp_path))
    rules = sorted({(f.rule, f.file) for f in findings})
    assert ("A001", "SELECT_K_TABLE_x.json") in rules


def test_artifacts_gate_flags_unparseable_json(tmp_path):
    (tmp_path / "BROKEN.json").write_text("{not json")
    findings, _ = run_artifacts(str(tmp_path))
    assert any(f.file == "BROKEN.json" and "does not parse" in f.message
               for f in findings)


def test_artifacts_gate_flags_v3_probe_with_missing_verdicts(tmp_path):
    import shutil
    (tmp_path / "tools").mkdir()
    shutil.copy(os.path.join(REPO, "tools", "pallas_probe.py"),
                tmp_path / "tools" / "pallas_probe.py")
    (tmp_path / "PALLAS_PROBE_tpu.json").write_text(json.dumps({
        "platform": "tpu",
        "fused": {"brute_force": {"fused_wins": True}}}))
    findings, _ = run_artifacts(str(tmp_path))
    (f,) = [f for f in findings if f.file == "PALLAS_PROBE_tpu.json"]
    assert "missing measured verdicts" in f.message
    assert "cagra" in f.message


# --------------------------------------------------------------- the gate

def test_repo_is_clean_under_committed_baseline():
    findings = run_kernels(REPO)
    baseline = load_baseline(os.path.join(REPO, "graftcheck_baseline.json"))
    new, suppressed = split_by_baseline(findings, baseline)
    assert new == [], "\n".join(f.format() for f in new)
    # the two deliberate interpret divergences stay enumerated
    assert {(f.rule, f.qualname) for f in suppressed} == {
        ("K004", "pallas_ring_shift"),
        ("K004", "fused_dispatch_explained")}


def test_kernel_scan_is_not_vacuous():
    # a resolver regression must not pass as "zero findings" silently:
    # the scan must have actually seen the fused engines
    s = kernel_stats(REPO)
    assert s["modules"] >= 1, s
    assert s["pallas_calls"] >= 8, s
    assert s["fused_kernels"] >= 4, s
    assert s["dma_sites"] >= 10, s


# --------------------------------------------------- CLI / queue contract

def test_cli_kernels_nonzero_on_injected_violation(tmp_path):
    root = inject(tmp_path, "k001_bad.py")
    proc = run_cli("--root", root, "--no-baseline", "--kernels",
                   "--no-kernel-sweep")
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "K001" in proc.stdout and "leaky_kernel" in proc.stdout
    assert "[kernels]" in proc.stdout  # the scan stats line


def test_queue_kernelcheck_step_gates_on_injected_k001(tmp_path):
    # the acceptance demonstration: tpu_queue2.sh's kernelcheck
    # pre-flight (same argv, pointed at a tree carrying a K001 pairing
    # bug) exits nonzero, so the pallas steps' marker guard never lets
    # a statically-broken kernel reach the chip window
    queue = open(os.path.join(REPO, "tools", "tpu_queue2.sh")).read()
    m = re.search(r"run_step kernelcheck \S+ timeout \d+ \\\n\s*"
                  r"python tools/graftcheck\.py ([^\n]+)", queue)
    assert m, "kernelcheck step missing from tpu_queue2.sh"
    argv = m.group(1).split()
    assert "--kernels" in argv
    # the pallas steps are gated on the kernelcheck marker
    assert queue.count("[ -f /tmp/q5_kernelcheck.done ] && \\") >= 3
    root = inject(tmp_path, "k001_bad.py")
    proc = run_cli(*argv, "--root", root, "--no-baseline",
                   "--no-kernel-sweep")
    assert proc.returncode == 1, proc.stdout + proc.stderr
    # the queue argv runs -q: the summary line is the contract there
    assert "1 new finding(s)" in proc.stdout


def test_cli_without_kernels_skips_k_rules(tmp_path):
    root = inject(tmp_path, "k001_bad.py")
    proc = run_cli("--root", root, "--no-baseline")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "K001" not in proc.stdout


def test_cli_no_kernel_sweep_requires_kernels():
    proc = run_cli("--no-kernel-sweep")
    assert proc.returncode == 2
    assert "--no-kernel-sweep requires --kernels" in proc.stderr


def test_cli_json_dump_carries_kernel_findings(tmp_path):
    root = inject(tmp_path, "k004_bad.py")
    out = tmp_path / "findings.json"
    proc = run_cli("--root", root, "--no-baseline", "--kernels",
                   "--no-kernel-sweep", "-q", "--json", str(out))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    doc = json.loads(out.read_text())
    (f,) = [e for e in doc["findings"] if e["rule"] == "K004"]
    assert f["qualname"] == "dispatch" and f["baselined"] is False
    assert f["file"].endswith("injected.py") and f["line"] > 0


def test_cli_artifacts_gate_runs_clean_on_the_repo():
    proc = run_cli("--artifacts")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "STALE pre-v3" in proc.stdout
    assert "[artifacts]" in proc.stdout
