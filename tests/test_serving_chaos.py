"""Chaos tests for the serving robustness layer (docs/serving.md
"Overload & failure semantics").

Each test injects one failure domain through the real dispatch path (the
``raft_tpu.testing.faults`` serving injectors wrap the searcher handle's
actual ``search`` callable) and pins an invariant the engine claims:

- an injected dispatch failure fails ONLY that batch's futures, with a
  typed :class:`BatchFailed` carrying the injected cause, and the engine
  keeps serving;
- an injected hang trips the circuit breaker within ``hang_timeout_s``
  (not after the full hang), admission sheds with :class:`CircuitOpen`,
  and a half-open probe closes the breaker (or re-opens it on failure);
- ``swap_index`` under concurrent submitters drops zero requests and
  every result is bit-identical to a solo search on whichever index
  actually served it;
- a degraded elastic restore (PR 3 ``allow_partial``) serves at reduced
  coverage and is promoted to a full restore via ``swap_index`` once
  ``verify_checkpoint`` reports the repaired checkpoint healthy;
- deadline and watermark sheds are typed rejections, never silent drops,
  and ``stop(drain=True)`` racing live submitters strands no future.

Timing note: on this CPU stack a real warmed search takes ~0.2-0.5 s end
to end, so every ``hang_timeout_s`` here keeps >= 2x headroom over that
(a tight timeout makes the watchdog "correctly" fail healthy batches).
"""

import threading
import time

import numpy as np
import pytest

from raft_tpu import serving
from raft_tpu.core.resources import Resources
from raft_tpu.neighbors import ivf_flat
from raft_tpu.parallel import comms as comms_mod
from raft_tpu.parallel import sharded
from raft_tpu.serving.engine import solo_reference
from raft_tpu.testing import faults

pytestmark = pytest.mark.fast

DIM = 16
K = 5


@pytest.fixture(scope="module")
def flat_index():
    rng = np.random.default_rng(3)
    db = rng.standard_normal((1500, DIM)).astype(np.float32)
    return ivf_flat.build(db, ivf_flat.IndexParams(n_lists=16))


@pytest.fixture()
def searcher(flat_index):
    # fresh handle per test: the injectors rebind .search on the handle,
    # so sharing one across tests would leak an armed fault
    return serving.ivf_flat_searcher(flat_index,
                                     ivf_flat.SearchParams(n_probes=8))


def _engine(s, **kw):
    kw.setdefault("max_batch", 8)
    kw.setdefault("max_wait_us", 5000)
    kw.setdefault("warm_ks", (K,))
    return serving.Engine(s, serving.EngineConfig(**kw))


def _q(rng):
    return rng.standard_normal(DIM).astype(np.float32)


# ------------------------------------------------- failure containment
def test_dispatch_failure_fails_only_that_batch(searcher):
    rng = np.random.default_rng(0)
    with _engine(searcher, hang_timeout_s=None) as eng:
        d, i = eng.search(_q(rng), K)
        assert d.shape == (K,)

        faults.fail_next_dispatch(searcher)
        victim = eng.submit(_q(rng), K)
        with pytest.raises(serving.BatchFailed) as ei:
            victim.result(timeout=60)
        assert isinstance(ei.value.cause, faults.InjectedFault)
        assert ei.value.__cause__ is ei.value.cause
        assert ei.value.hang is False

        # the loop survived: subsequent requests ride fresh batches
        futs = [eng.submit(_q(rng), K) for _ in range(12)]
        for f in futs:
            d, i = f.result(timeout=60)
            assert d.shape == (K,) and i.shape == (K,)
        eng.drain(60)

        # exactly the one batch failed; ordinary errors never open the
        # breaker (that verdict belongs to the hang watchdog alone)
        snap = eng.stats.snapshot()
        assert snap["n_failed"] == 1
        assert snap["n_batch_errors"] == 1
        assert snap["n_hangs"] == 0
        assert eng.breaker.state == "closed"
        assert eng.health()["status"] == "ok"


def test_dispatch_failure_spares_concurrent_other_k_batch(searcher):
    """Two same-instant batches (distinct k never coalesces): the armed
    fault kills whichever launches first; every rider of the OTHER batch
    still resolves with rows."""
    rng = np.random.default_rng(1)
    with _engine(searcher, hang_timeout_s=None, max_wait_us=20000) as eng:
        faults.fail_next_dispatch(searcher)
        a = [eng.submit(_q(rng), K) for _ in range(3)]
        b = [eng.submit(_q(rng), K + 2) for _ in range(3)]
        outcomes = {"failed": 0, "ok": 0}
        for f in a + b:
            try:
                d, i = f.result(timeout=60)
                assert d.shape[0] in (K, K + 2)
                outcomes["ok"] += 1
            except serving.BatchFailed as e:
                assert isinstance(e.cause, faults.InjectedFault)
                outcomes["failed"] += 1
        # one whole batch (3 riders) failed, the other completed
        assert outcomes == {"failed": 3, "ok": 3}
        eng.drain(60)
        assert eng.stats.snapshot()["n_batch_errors"] == 1


# ----------------------------------------------- watchdog + breaker
def test_hang_trips_breaker_then_half_open_probe_closes(searcher):
    rng = np.random.default_rng(2)
    with _engine(searcher, hang_timeout_s=1.0, breaker_cooldown_s=0.5,
                 max_wait_us=0) as eng:
        eng.search(_q(rng), K)
        assert eng.health()["status"] == "ok"

        faults.hang_next_dispatch(searcher, hang_s=3.0)
        t0 = time.perf_counter()
        victim = eng.submit(_q(rng), K)
        with pytest.raises(serving.BatchFailed) as ei:
            victim.result(timeout=60)
        elapsed = time.perf_counter() - t0
        assert ei.value.hang is True
        # the watchdog's verdict, not the hang's end: the 3 s sleep is
        # still in progress when the future fails
        assert elapsed < 2.5, f"hang verdict took {elapsed:.2f}s"
        assert eng.breaker.state == "open"
        assert eng.health()["status"] == "unhealthy"

        with pytest.raises(serving.CircuitOpen):
            eng.submit(np.zeros(DIM, np.float32), K)
        snap = eng.stats.snapshot()
        assert snap["n_hangs"] == 1
        assert snap["n_breaker_trips"] == 1
        assert snap["n_rejected_breaker"] == 1

        # let the stuck dispatch thread drain its sleep, then probe:
        # open -> half_open at admission, a completed batch closes it
        time.sleep(max(0.0, t0 + 3.4 - time.perf_counter()))
        probe = eng.submit(_q(rng), K)
        d, i = probe.result(timeout=60)
        assert d.shape == (K,)
        eng.drain(60)
        assert eng.breaker.state == "closed"
        assert eng.health()["status"] == "ok"


def test_half_open_probe_failure_reopens_breaker(searcher):
    rng = np.random.default_rng(4)
    with _engine(searcher, hang_timeout_s=0.8, breaker_cooldown_s=0.4,
                 max_wait_us=0) as eng:
        eng.search(_q(rng), K)
        faults.hang_next_dispatch(searcher, hang_s=2.0)
        t0 = time.perf_counter()
        victim = eng.submit(_q(rng), K)
        with pytest.raises(serving.BatchFailed):
            victim.result(timeout=60)
        assert eng.breaker.state == "open"

        # hang drained + cooldown elapsed -> next admission is the probe;
        # arm a plain failure so the probe batch fails
        time.sleep(max(0.0, t0 + 2.5 - time.perf_counter()))
        faults.fail_next_dispatch(searcher)
        probe = eng.submit(_q(rng), K)
        with pytest.raises(serving.BatchFailed) as ei:
            probe.result(timeout=60)
        assert isinstance(ei.value.cause, faults.InjectedFault)
        eng.drain(60)
        assert eng.breaker.state == "open"  # probe verdict re-opened
        with pytest.raises(serving.CircuitOpen):
            eng.submit(np.zeros(DIM, np.float32), K)


# ----------------------------------------------------------- hot swap
def test_swap_under_concurrent_load_zero_drops_bit_identical(flat_index):
    rng = np.random.default_rng(5)
    db2 = rng.standard_normal((1500, DIM)).astype(np.float32)
    index2 = ivf_flat.build(db2, ivf_flat.IndexParams(n_lists=16))
    s1 = serving.ivf_flat_searcher(flat_index,
                                   ivf_flat.SearchParams(n_probes=8))
    s2 = serving.ivf_flat_searcher(index2,
                                   ivf_flat.SearchParams(n_probes=8))
    n_threads, n_per = 6, 8
    results = [[] for _ in range(n_threads)]
    errors = []

    with _engine(s1) as eng:
        def worker(ti):
            trng = np.random.default_rng(100 + ti)
            for _ in range(n_per):
                q = _q(trng)
                try:
                    f = eng.submit(q, K)
                    d, i = f.result(timeout=120)
                    results[ti].append((q, d, i, f.searcher, f.placement))
                except BaseException as e:  # noqa: B036 — any failure
                    errors.append(e)       # breaks the zero-drop claim

        threads = [threading.Thread(target=worker, args=(ti,))
                   for ti in range(n_threads)]
        for t in threads:
            t.start()
        time.sleep(0.2)
        old = eng.swap_index(s2)  # warm + swap while the load runs
        assert old is s1
        for t in threads:
            t.join()

        assert errors == []
        flat = [row for rows in results for row in rows]
        assert len(flat) == n_threads * n_per  # zero dropped requests

        # after the swap every new request serves from the new index
        q = _q(rng)
        f = eng.submit(q, K)
        f.result(timeout=120)
        assert f.searcher is s2
        snap = eng.stats.snapshot()
        assert snap["n_swaps"] == 1
        assert snap["coverage_transitions"] == [(1.0, 1.0)]

    # exactness oracle: each result bit-identical to a solo search on
    # whichever index actually served it, at the same (row, bucket)
    for q, d, i, served_by, (row, bucket) in flat:
        assert served_by in (s1, s2)
        d_ref, i_ref = solo_reference(served_by, q, K, row, bucket)
        assert np.array_equal(d, d_ref)
        assert np.array_equal(i, i_ref)


def test_swap_rejects_mismatched_index(searcher, flat_index):
    rng = np.random.default_rng(6)
    db = rng.standard_normal((300, DIM * 2)).astype(np.float32)
    wrong = serving.ivf_flat_searcher(
        ivf_flat.build(db, ivf_flat.IndexParams(n_lists=4)),
        ivf_flat.SearchParams(n_probes=4))
    with _engine(searcher) as eng:
        with pytest.raises(ValueError, match="dim mismatch"):
            eng.swap_index(wrong)
        assert eng.searcher is searcher  # unchanged after the reject


# ------------------------------------- degraded restore -> promotion
def test_degraded_elastic_restore_promotion(tmp_path):
    """Serve a partial restore (coverage 7/8) and promote it to the full
    index once the repaired checkpoint verifies healthy — the PR 3
    degraded-restore story closed end to end through the engine."""
    n_rows, n_shards = 2048, 8
    rng = np.random.default_rng(11)
    x = rng.standard_normal((n_rows, DIM)).astype(np.float32)
    comms = comms_mod.init_comms(axis="serving_chaos")
    idx = sharded.build_ivf_flat(
        comms, x, ivf_flat.IndexParams(n_lists=16, kmeans_n_iters=2),
        res=Resources(seed=0))
    prefix = str(tmp_path / "flat")
    sharded.serialize_ivf_flat(idx, prefix)
    assert sharded.verify_checkpoint(prefix)["ok"]

    dead = 3
    faults.delete_rank_file(prefix, dead)
    assert not sharded.verify_checkpoint(prefix)["ok"]
    el = sharded.deserialize_ivf_flat_elastic(prefix, allow_partial=True)
    degraded = serving.elastic_searcher(
        el, ivf_flat.SearchParams(n_probes=16))
    assert degraded.coverage == (n_shards - 1) / n_shards

    with _engine(degraded, max_wait_us=1000) as eng:
        h = eng.health()
        assert h["status"] == "degraded"
        assert h["coverage"] == (n_shards - 1) / n_shards

        d, i = eng.search(x[0], K)
        lo, hi = dead * (n_rows // n_shards), (dead + 1) * (n_rows // n_shards)
        assert not np.any((np.asarray(i) >= lo) & (np.asarray(i) < hi))

        # repair: rewrite the checkpoint, verify, THEN promote
        sharded.serialize_ivf_flat(idx, prefix)
        assert sharded.verify_checkpoint(prefix)["ok"]
        el_full = sharded.deserialize_ivf_flat_elastic(prefix)
        full = serving.elastic_searcher(
            el_full, ivf_flat.SearchParams(n_probes=16))
        assert full.coverage == 1.0
        eng.swap_index(full)

        assert eng.health()["status"] == "ok"
        snap = eng.stats.snapshot()
        assert snap["coverage"] == 1.0
        assert snap["coverage_transitions"] == [
            ((n_shards - 1) / n_shards, 1.0)]

        # query 0's nearest row is itself; reachable again post-promotion
        d2, i2 = eng.search(x[0], K)
        assert 0 in np.asarray(i2)


# ------------------------------------------------ shedding is typed
def test_deadline_shed_is_typed_never_silent(searcher):
    with _engine(searcher, max_batch=64, max_wait_us=30_000_000) as eng:
        # the flush policy alone would hold this request for 30 s
        fut = eng.submit(np.zeros(DIM, np.float32), K, deadline_ms=60)
        t0 = time.perf_counter()
        with pytest.raises(serving.DeadlineExceeded):
            fut.result(timeout=60)
        assert time.perf_counter() - t0 < 5.0  # shed at the deadline
        snap = eng.stats.snapshot()
        assert snap["n_shed_deadline"] == 1
        assert eng.health()["status"] == "ok"  # shed != sick
        eng.stop(drain=False)


def test_overload_watermark_shed_and_recovery(searcher):
    with faults.slow_searcher(searcher, 0.15), \
            _engine(searcher, max_batch=1, max_wait_us=0, max_inflight=1,
                    queue_high_watermark=4, queue_low_watermark=1,
                    hang_timeout_s=None) as eng:
        futs, rejected = [], 0
        for _ in range(12):
            try:
                futs.append(eng.submit(np.zeros(DIM, np.float32), K))
            except serving.Overloaded:
                rejected += 1
        assert rejected > 0
        assert eng.health()["status"] == "degraded"  # latched
        assert eng.stats.snapshot()["n_rejected_overload"] == rejected

        # every ADMITTED request still completes normally
        for f in futs:
            d, i = f.result(timeout=120)
            assert d.shape == (K,)
        eng.drain(120)

        # drained under the low watermark -> admission unlatches
        f = eng.submit(np.zeros(DIM, np.float32), K)
        assert f.result(timeout=120)[0].shape == (K,)
        assert eng.health()["status"] == "ok"


# --------------------------------------------- stop() vs submitters
def test_stop_drain_races_concurrent_submitters(searcher):
    """6 threads submit in a loop while the main thread stops the
    engine: late submits get a typed EngineStopped, every future handed
    out resolves with rows (drain launches the whole queue), and no
    future is left pending — the stranded-future invariant."""
    # watermark at the queue cap: this test targets the stop race, and
    # 6 unthrottled submitters would otherwise latch overload shedding
    eng = _engine(searcher, queue_high_watermark=4096).start()
    futures = []
    lock = threading.Lock()
    stopped_submitters = []

    def worker(ti):
        trng = np.random.default_rng(200 + ti)
        for _ in range(1000):
            try:
                f = eng.submit(_q(trng), K)
            except serving.EngineStopped:
                stopped_submitters.append(ti)
                return
            with lock:
                futures.append(f)
            time.sleep(0.002)
        raise AssertionError("engine never stopped")

    threads = [threading.Thread(target=worker, args=(ti,))
               for ti in range(6)]
    for t in threads:
        t.start()
    time.sleep(0.1)
    eng.stop(drain=True)
    for t in threads:
        t.join()

    assert len(stopped_submitters) == 6  # every late submit was typed
    assert len(futures) > 0
    for f in futures:
        assert f.done()  # stop() returned -> nothing still pending
        d, i = f.result(timeout=0)
        assert d.shape == (K,) and i.shape == (K,)
    assert eng.stats.snapshot()["n_completed"] == len(futures)


# ------------------------------------- amplified interleavings (slow tier)
@pytest.mark.slow
@pytest.mark.interleave
def test_stop_drain_race_amplified(searcher):
    """The stop-drain stranded-future invariant, re-run under the seeded
    schedule amplifier (raft_tpu.testing.interleave): forced preemptions
    inside raft_tpu/serving must not surface a dropped or unresolved
    future at any seed. Seed base via RAFT_TPU_INTERLEAVE_SEED."""
    from raft_tpu.testing.interleave import InterleaveAmplifier, seeds

    for seed in seeds(10):
        eng = _engine(searcher, queue_high_watermark=4096)
        futures = []
        lock = threading.Lock()

        def worker(ti):
            trng = np.random.default_rng(300 + ti)
            for _ in range(30):
                try:
                    f = eng.submit(_q(trng), K)
                except serving.EngineStopped:
                    return
                with lock:
                    futures.append(f)

        with InterleaveAmplifier(seed=seed, yield_probability=0.05,
                                 path_filters=("raft_tpu/serving",)):
            eng.start()
            threads = [threading.Thread(target=worker, args=(ti,))
                       for ti in range(3)]
            for t in threads:
                t.start()
            time.sleep(0.05)
            eng.stop(drain=True)
            for t in threads:
                t.join()

        for f in futures:
            assert f.done(), f"seed {seed}: stranded future"
            d, i = f.result(timeout=0)
            assert d.shape == (K,) and i.shape == (K,), seed
