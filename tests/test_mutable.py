"""Crash-consistent mutable indexes (raft_tpu/neighbors/mutable.py).

Covers the write path's durability contract end to end: WAL framing +
torn-tail/corrupt classification, select_k_filtered standing filter,
add/upsert/delete semantics, bit-stable merged search, checkpoint +
replay recovery, kill -9 at every injected point (mid-append, torn
tail, mid-compaction, mid-publish), compaction spans/counters 1:1
reconciliation, Engine/Fleet hot-swap publication, and the amplified
interleave suite (concurrent writers + searchers + compactor with exact
counter reconciliation per seed).
"""

import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from raft_tpu.core.errors import IntegrityError, RaftError
from raft_tpu.neighbors import ivf_flat, mutable
from raft_tpu.obs import metrics as obs_metrics
from raft_tpu.obs import spans as obs_spans
from raft_tpu.ops.select_k import select_k, select_k_filtered
from raft_tpu.testing import faults
from raft_tpu.testing.interleave import InterleaveAmplifier, seeds

from _mutable_kill_child import DIM as CHILD_DIM
from _mutable_kill_child import apply_op, make_ops

DIM = 8


def _writer(tmp_path, **kw):
    kw.setdefault("dim", DIM)
    kw.setdefault("registry", obs_metrics.Registry())
    kw.setdefault("span_sink", obs_spans.ListSink())
    kw.setdefault("group_window_s", 0.0)
    return mutable.MutableIvf(str(tmp_path / "idx"), **kw)


def _metric(writer, name, *labels):
    fam = writer.registry.get(name)
    assert fam is not None, name
    return dict(fam.collect()).get(labels, type("z", (), {"value": 0})).value


def _live_state(writer):
    """(ids, vectors) of every live row sorted by id — the bit-identity
    comparison surface (vectors round-trip the WAL as raw float32)."""
    snap = writer._compaction_snapshot()
    order = np.argsort(snap.ids, kind="stable")
    return snap.ids[order], snap.vectors[order]


# ------------------------------------------------------------------- WAL


def test_wal_roundtrip_and_record_spans(tmp_path):
    path = str(tmp_path / "wal.log")
    wal = mutable.WriteAheadLog(path, group_window_s=0.0)
    rng = np.random.default_rng(0)
    for op, ids in ((mutable.OP_ADD, [0, 1]), (mutable.OP_UPSERT, [1]),
                    (mutable.OP_DELETE, [0])):
        n = len(ids)
        vecs = rng.standard_normal((n, 4)).astype(np.float32) \
            if op != mutable.OP_DELETE else np.zeros((0, 4), np.float32)
        wal.commit(op, np.asarray(ids, np.int32), vecs)
    wal.close()

    scan = mutable.read_wal(path)
    assert scan.status == "ok" and scan.error is None
    assert [r.lsn for r in scan.records] == [1, 2, 3]
    assert [r.op for r in scan.records] == [
        mutable.OP_ADD, mutable.OP_UPSERT, mutable.OP_DELETE]
    assert list(scan.records[0].ids) == [0, 1]
    # footer-less WAL frames are visible to the PR-3 byte injectors
    from raft_tpu.core.serialize import record_spans
    assert len(record_spans(path)) == 3


@pytest.mark.parametrize("mode", ["truncate", "flip"])
def test_wal_torn_tail_is_typed_and_positional(tmp_path, mode):
    path = str(tmp_path / "wal.log")
    wal = mutable.WriteAheadLog(path, group_window_s=0.0)
    for i in range(3):
        wal.commit(mutable.OP_ADD, np.asarray([i], np.int32),
                   np.full((1, 4), float(i), np.float32))
    wal.close()
    faults.tear_wal_tail(path, mode=mode)

    scan = mutable.read_wal(path)
    assert scan.status == "torn_tail"
    assert isinstance(scan.error, IntegrityError)
    assert scan.error.reason == "torn_tail"
    # the durable prefix survives intact
    assert [r.lsn for r in scan.records] == [1, 2]


def test_wal_damage_mid_file_is_corrupt_not_torn(tmp_path):
    path = str(tmp_path / "wal.log")
    wal = mutable.WriteAheadLog(path, group_window_s=0.0)
    for i in range(3):
        wal.commit(mutable.OP_ADD, np.asarray([i], np.int32),
                   np.full((1, 4), float(i), np.float32))
    wal.close()
    faults.flip_record_byte(path, 1)  # bytes FOLLOW the damaged frame

    scan = mutable.read_wal(path)
    assert scan.status == "corrupt"
    assert isinstance(scan.error, IntegrityError)
    assert scan.error.reason == "corrupt"


def test_wal_bad_header_is_corrupt(tmp_path):
    path = str(tmp_path / "wal.log")
    with open(path, "wb") as f:
        f.write(b"not a wal at all")
    scan = mutable.read_wal(path)
    assert scan.status == "corrupt"
    assert scan.error.reason == "corrupt"


def test_wal_group_commit_batches_appends(tmp_path):
    """Concurrent writers share fsyncs: every committed lsn is durable,
    and the writer-facing invariant ack => durable holds throughout."""
    path = str(tmp_path / "wal.log")
    wal = mutable.WriteAheadLog(path, group_window_s=0.002)
    errors = []

    def hammer(tid):
        try:
            for i in range(10):
                lsn = wal.commit(mutable.OP_ADD,
                                 np.asarray([tid * 100 + i], np.int32),
                                 np.zeros((1, 4), np.float32))
                assert wal.durable_lsn >= lsn
        except (RaftError, ValueError) as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=hammer, args=(t,)) for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wal.close()
    assert not errors
    scan = mutable.read_wal(path)
    assert scan.status == "ok"
    assert sorted(r.lsn for r in scan.records) == list(range(1, 41))


# ------------------------------------------------------- select_k_filtered


def test_select_k_filtered_removes_and_counts():
    values = np.asarray([[1.0, 2.0, 3.0, 4.0, 5.0]], np.float32)
    ids = np.asarray([[10, 11, 12, 13, -1]], np.int32)
    words = np.zeros(1, np.uint32)
    for allowed in (10, 12, 13):
        words[allowed // 32] |= np.uint32(1) << np.uint32(allowed % 32)
    v, i, n_filt = select_k_filtered(values, 3, ids, words,
                                     pad_rules=False)
    assert list(np.asarray(i)[0]) == [10, 12, 13]
    assert list(np.asarray(v)[0]) == [1.0, 3.0, 4.0]
    # 11 was a live candidate removed by the bitset; -1 padding is NOT
    # counted as filtered
    assert int(n_filt) == 1


def test_select_k_filtered_matches_select_k_on_allowed_subset():
    rng = np.random.default_rng(7)
    values = rng.standard_normal((4, 64)).astype(np.float32)
    ids = np.tile(np.arange(64, dtype=np.int32), (4, 1))
    words = np.zeros(2, np.uint32)
    allowed = rng.choice(64, size=40, replace=False)
    for a in allowed:
        words[a // 32] |= np.uint32(1) << np.uint32(a % 32)
    v, i, n_filt = select_k_filtered(values, 8, ids, words,
                                     select_min=True, pad_rules=False)
    mask = np.zeros(64, bool)
    mask[allowed] = True
    ref_v, ref_i = select_k(
        np.where(mask[None, :], values, np.inf), 8, True,
        indices=np.where(mask[None, :], ids, -1), pad_rules=False)
    np.testing.assert_array_equal(np.asarray(v), np.asarray(ref_v))
    np.testing.assert_array_equal(np.asarray(i), np.asarray(ref_i))
    assert int(n_filt) == 4 * (64 - 40)


# ------------------------------------------------------- writer semantics


def test_add_upsert_delete_search_semantics(tmp_path):
    w = _writer(tmp_path)
    rng = np.random.default_rng(1)
    vecs = rng.standard_normal((10, DIM)).astype(np.float32)
    ids = w.add(vecs)
    assert list(ids) == list(range(10))

    # exact self-query: nearest neighbor of row 3 is id 3
    _, i = w.search(vecs[3], 1)
    assert int(np.asarray(i).ravel()[0]) == 3

    # upsert moves id 3 far away; a fresh query there finds it
    far = np.full((1, DIM), 50.0, np.float32)
    w.upsert(far, [3])
    d, i = w.search(far, 1)
    assert int(np.asarray(i).ravel()[0]) == 3
    assert float(np.asarray(d).ravel()[0]) < 1e-3

    # delete: the id never surfaces again, even at k = everything
    w.delete([3])
    _, i = w.search(far, 10)
    assert 3 not in set(np.asarray(i).ravel().tolist())
    assert w.size == 9

    # explicit-id collision with a live row is a typed validation error
    with pytest.raises(ValueError, match="upsert"):
        w.add(vecs[:1], ids=[4])
    w.close()


def test_search_is_bit_stable_across_calls_and_snapshots(tmp_path):
    w = _writer(tmp_path)
    rng = np.random.default_rng(2)
    w.add(rng.standard_normal((64, DIM)).astype(np.float32))
    q = rng.standard_normal((4, DIM)).astype(np.float32)
    d1, i1 = w.search(q, 5)
    w.delete([0])  # invalidate the device snapshot
    w.upsert(rng.standard_normal((1, DIM)).astype(np.float32), [0])
    d2, i2 = w.search(q, 5)
    d3, i3 = w.search(q, 5)
    np.testing.assert_array_equal(np.asarray(d2), np.asarray(d3))
    np.testing.assert_array_equal(np.asarray(i2), np.asarray(i3))
    w.close()


def test_deleted_base_ids_filtered_after_compaction(tmp_path):
    """Tombstones fold into select as a standing filter over BASE rows
    (post-compaction residents), with the filtered_rows counter live."""
    w = _writer(tmp_path)
    rng = np.random.default_rng(3)
    vecs = rng.standard_normal((40, DIM)).astype(np.float32)
    w.add(vecs)
    comp = mutable.Compactor(w)
    assert comp.run_once("manual") == "ok"
    assert w.stats()["base_rows"] == 40 and w.stats()["delta_rows"] == 0

    victim = 7
    w.delete([victim])
    assert w.stats()["tombstone_live_ratio"] > 0
    _, i = w.search(vecs[victim], 40)
    got = set(np.asarray(i).ravel().tolist())
    assert victim not in got
    assert _metric(w, "raft_tpu_mutable_filtered_rows_total", w.name) > 0

    # upsert of a base-resident id: the stale base copy is masked too
    w.upsert(np.full((1, DIM), 30.0, np.float32), [11])
    _, i = w.search(vecs[11], 40)
    ids = np.asarray(i).ravel().tolist()
    assert ids.count(11) <= 1  # never both copies
    w.close()


def test_concurrent_adds_never_share_auto_ids(tmp_path):
    """Auto-id assignment commits in the same critical section as the
    WAL append + apply: two adds racing can never both observe one
    next_id and be acknowledged with the same id (the second would
    silently overwrite the first — put is insert-or-replace)."""
    w = _writer(tmp_path, group_window_s=0.002)
    got: list = []
    errors: list = []

    def adder(tid):
        rng = np.random.RandomState(100 + tid)
        try:
            for _ in range(6):
                ids = w.add(rng.randn(2, DIM).astype(np.float32))
                got.extend(int(i) for i in ids)
        except (RaftError, ValueError) as e:  # pragma: no cover
            errors.append(e)

    with InterleaveAmplifier(
            seed=5, path_filters=("neighbors/mutable.py",)):
        threads = [threading.Thread(target=adder, args=(t,))
                   for t in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    assert not errors, errors
    assert len(got) == 3 * 6 * 2
    assert len(set(got)) == len(got), \
        "two concurrent adds were acknowledged with the same id"
    assert w.size == len(got)
    w.close()


def test_ivf_pq_compaction_never_duplicates_upserted_ids(tmp_path):
    """The ivf_pq extend path must not re-extend an id already resident
    in the base: extend does not dedupe ids and the standing filter is
    id-keyed, so a second physical row would resurface the stale
    pre-upsert vector. Superseded rows stay in the delta instead."""
    from raft_tpu.neighbors import ivf_pq

    rng = np.random.default_rng(23)
    w = _writer(tmp_path, family="ivf_pq",
                index_params=ivf_pq.IndexParams(n_lists=2))
    vecs = rng.standard_normal((32, DIM)).astype(np.float32)
    w.add(vecs)
    comp = mutable.Compactor(w, min_rows=1)
    assert comp.run_once("manual") == "ok"
    assert w.stats()["base_rows"] == 32

    # upsert a base-resident id far away, plus one brand-new row
    far = np.full((1, DIM), 25.0, np.float32)
    w.upsert(far, [7])
    w.add(rng.standard_normal((1, DIM)).astype(np.float32))  # id 32
    assert comp.run_once("manual") == "ok"

    # the fresh row was absorbed; the superseded one stays in the delta
    # and the base holds exactly ONE physical row for its id
    assert w.stats()["delta_rows"] == 1
    assert list(mutable._index_ids(w.base)).count(7) == 1
    d, i = w.search(far, 33)
    ids = np.asarray(i).ravel().tolist()
    assert ids.count(7) == 1, "stale base copy surfaced after compaction"
    assert ids[0] == 7  # the upserted (exact, delta-resident) location
    assert float(np.asarray(d).ravel()[0]) < 1e-3
    w.close()


# ------------------------------------------------------ recovery + replay


def test_recovery_replays_wal_bit_identical(tmp_path):
    w = _writer(tmp_path)
    rng = np.random.default_rng(4)
    w.add(rng.standard_normal((20, DIM)).astype(np.float32))
    w.delete([2, 4])
    w.upsert(rng.standard_normal((2, DIM)).astype(np.float32), [0, 1])
    q = rng.standard_normal((3, DIM)).astype(np.float32)
    d1, i1 = w.search(q, 6)
    w.close()

    w2 = _writer(tmp_path)
    assert w2.recovery["status"] == "ok"
    assert w2.recovery["replayed"] == 3
    d2, i2 = w2.search(q, 6)
    np.testing.assert_array_equal(np.asarray(d1), np.asarray(d2))
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
    assert _metric(w2, "raft_tpu_mutable_replays_total", w2.name, "ok") == 1
    # replay is surfaced as a span too
    kinds = [s["kind"] for s in w2.span_sink.records]
    assert "wal_replay" in kinds
    w2.close()


def test_torn_tail_recovery_is_typed_never_a_crash(tmp_path):
    w = _writer(tmp_path)
    w.add(np.ones((4, DIM), np.float32))
    w.add(2.0 * np.ones((4, DIM), np.float32))
    faults.tear_wal_tail(w, mode="flip")
    w.close()

    w2 = _writer(tmp_path)
    rec = w2.recovery
    assert rec["status"] == "torn_tail"
    assert isinstance(rec["error"], IntegrityError)
    assert rec["error"].reason == "torn_tail"
    assert rec["applied_lsn"] == 1  # the torn frame's writes are gone
    assert _metric(w2, "raft_tpu_mutable_replays_total",
                   w2.name, "torn_tail") == 1
    # the log was truncated: reopening again is clean
    w2.close()
    w3 = _writer(tmp_path)
    assert w3.recovery["status"] == "ok"
    w3.close()


def test_corrupt_wal_raises_typed(tmp_path):
    w = _writer(tmp_path)
    for i in range(3):
        w.add(np.full((2, DIM), float(i), np.float32))
    w.close()
    faults.flip_record_byte(str(tmp_path / "idx" / "wal.log"), 1)
    with pytest.raises(IntegrityError) as ei:
        _writer(tmp_path)
    assert ei.value.reason == "corrupt"


def test_checkpoint_trims_wal_and_restores(tmp_path):
    w = _writer(tmp_path)
    rng = np.random.default_rng(5)
    w.add(rng.standard_normal((12, DIM)).astype(np.float32))
    w.checkpoint()
    w.delete([0])  # post-checkpoint: must survive via the WAL tail
    q = rng.standard_normal((2, DIM)).astype(np.float32)
    d1, i1 = w.search(q, 4)
    w.close()

    assert mutable.read_wal(str(tmp_path / "idx" / "wal.log")).records, \
        "post-checkpoint write should be in the trimmed WAL"
    w2 = _writer(tmp_path)
    assert w2.recovery["replayed"] == 1
    d2, i2 = w2.search(q, 4)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
    np.testing.assert_array_equal(np.asarray(d1), np.asarray(d2))
    w2.close()


def test_restored_metric_matches_persisted_state(tmp_path):
    """Reopening a directory resolves the metric from the restored
    state (checkpointed base, or the metric persisted alongside it),
    never from the absent constructor args — an InnerProduct index must
    keep max-close selection across a crash/restart cycle."""
    from raft_tpu.ops.distance import DistanceType

    rng = np.random.default_rng(20)
    w = _writer(tmp_path, index_params=ivf_flat.IndexParams(
        n_lists=2, metric=DistanceType.InnerProduct))
    vecs = rng.standard_normal((16, DIM)).astype(np.float32)
    w.add(vecs)
    q = rng.standard_normal((2, DIM)).astype(np.float32)
    d1, i1 = w.search(q, 4)
    w.checkpoint()
    w.close()

    # base-less checkpoint: the metric rides the checkpoint itself
    w2 = _writer(tmp_path)  # reopen passes no base / no index_params
    assert w2.metric == DistanceType.InnerProduct
    d2, i2 = w2.search(q, 4)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
    np.testing.assert_array_equal(np.asarray(d1), np.asarray(d2))

    # a compaction on the reopened writer rebuilds in the SAME space...
    comp = mutable.Compactor(w2)
    assert comp.run_once("manual") == "ok"
    assert w2.base.metric == DistanceType.InnerProduct
    _, i3 = w2.search(q, 4)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i3))
    w2.close()

    # ...and a restore WITH a base adopts the base's metric
    w3 = _writer(tmp_path)
    assert w3.metric == DistanceType.InnerProduct
    _, i4 = w3.search(q, 4)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i4))
    w3.close()


# ------------------------------------------------------------- compaction


def test_compaction_reason_vocabulary_is_closed(tmp_path):
    w = _writer(tmp_path)
    comp = mutable.Compactor(w)
    with pytest.raises(ValueError, match="unknown compaction reason"):
        comp.request("because")
    with pytest.raises(ValueError, match="unknown compaction reason"):
        comp.run_once("vibes")
    w.close()


def test_compaction_counters_reconcile_1_to_1_with_spans(tmp_path):
    w = _writer(tmp_path)
    rng = np.random.default_rng(6)
    w.add(rng.standard_normal((30, DIM)).astype(np.float32))
    comp = mutable.Compactor(w)
    assert comp.run_once("manual") == "ok"
    w.delete(list(range(5)))
    assert comp.run_once("tombstone_ratio") == "ok"
    with faults.crash_compactor(w):
        assert comp.run_once("delta_threshold") == "failed"

    spans = [s for s in w.span_sink.records if s["kind"] == "compaction"]
    by_key: dict = {}
    for s in spans:
        by_key[(s["reason"], s["outcome"])] = \
            by_key.get((s["reason"], s["outcome"]), 0) + 1
    fam = w.registry.get("raft_tpu_mutable_compactions_total")
    counted = {(labels[1], labels[2]): child.value
               for labels, child in fam.collect()}
    assert counted == by_key  # exactly 1:1, per (reason, outcome)
    assert counted[("manual", "ok")] == 1
    assert counted[("delta_threshold", "failed")] == 1
    w.close()


def test_compaction_auto_triggers(tmp_path):
    w = _writer(tmp_path)
    rng = np.random.default_rng(7)
    comp = mutable.Compactor(w, delta_threshold=16, tombstone_ratio=0.2)
    w.add(rng.standard_normal((20, DIM)).astype(np.float32))
    assert comp._auto_reason() == "delta_threshold"
    assert comp.run_once(comp._auto_reason()) == "ok"
    assert comp._auto_reason() is None
    w.delete(list(range(6)))
    assert comp._auto_reason() == "tombstone_ratio"
    w.close()


def test_compaction_stall_trips_flight_recorder(tmp_path):
    w = _writer(tmp_path)
    w.add(np.random.default_rng(8).standard_normal((8, DIM))
          .astype(np.float32))
    dumps = []

    class Target:
        def swap_index(self, searcher):
            return searcher

        def dump_diagnostics(self, reason="manual"):
            dumps.append(reason)
            return "bundle"

        @property
        def searcher_generation(self):
            return 1

    class SlowCompactor(mutable.Compactor):
        def _build(self, snap):
            time.sleep(0.2)
            return super()._build(snap)

    comp = SlowCompactor(w, publish=Target(), stall_timeout_s=0.02)
    assert comp.run_once("manual") == "ok"  # a stall detects, not aborts
    assert dumps == ["compaction_stall"]
    assert _metric(w, "raft_tpu_mutable_compaction_stalls_total",
                   w.name) == 1
    stall_spans = [s for s in w.span_sink.records
                   if s["kind"] == "compaction_stall"]
    assert len(stall_spans) == 1 and stall_spans[0]["reason"] == "manual"
    w.close()


def test_background_compactor_thread_runs_and_stops(tmp_path):
    w = _writer(tmp_path)
    rng = np.random.default_rng(9)
    comp = mutable.Compactor(w, delta_threshold=8, poll_s=0.005,
                             min_rows=1)
    comp.start()
    try:
        w.add(rng.standard_normal((32, DIM)).astype(np.float32))
        deadline = time.monotonic() + 10.0
        while comp.runs == 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert comp.runs > 0, "auto compaction never fired"
    finally:
        comp.stop()
    assert w.stats()["base_rows"] > 0
    w.close()


# ----------------------------------------------------- serving integration


def _mutable_engine(w, **kw):
    from raft_tpu import serving

    kw.setdefault("max_batch", 4)
    kw.setdefault("max_wait_us", 2000)
    kw.setdefault("warm_ks", (3,))
    kw.setdefault("warm_buckets", (1, 4))
    searcher = serving.mutable_ivf_searcher(w)
    return serving.Engine(searcher, serving.EngineConfig(**kw))


def test_engine_writer_surface_and_hot_swap_publish(tmp_path):
    from raft_tpu import serving

    w = _writer(tmp_path)
    rng = np.random.default_rng(10)
    vecs = rng.standard_normal((24, DIM)).astype(np.float32)
    with _mutable_engine(w) as eng:
        # the writer surface is the mutable index behind the searcher
        eng.writer().add(vecs)
        d, i = eng.submit(vecs[5], 3).result(timeout=60)
        assert int(np.asarray(i).ravel()[0]) == 5

        comp = mutable.Compactor(w, publish=eng)
        assert comp.run_once("manual") == "ok"
        assert eng.searcher_generation == 1  # published via hot swap
        span = [s for s in w.span_sink.records
                if s["kind"] == "compaction"][-1]
        assert span["searcher_gen"] == 1  # the generation breadcrumb

        # zero dropped requests across the swap; deletes keep working
        eng.writer().delete([5])
        d, i = eng.submit(vecs[5], 3).result(timeout=60)
        assert 5 not in set(np.asarray(i).ravel().tolist())
    w.close()


def test_engine_writer_surface_is_typed_for_immutable_indexes():
    from raft_tpu import serving

    rng = np.random.default_rng(11)
    db = rng.standard_normal((64, DIM)).astype(np.float32)
    idx = ivf_flat.build(db, ivf_flat.IndexParams(n_lists=4))
    searcher = serving.ivf_flat_searcher(idx)
    eng = serving.Engine(searcher, serving.EngineConfig(max_batch=2))
    with pytest.raises(TypeError, match="write surface"):
        eng.writer()


def test_fleet_rolling_swap_publish(tmp_path):
    from raft_tpu import serving

    w = _writer(tmp_path)
    rng = np.random.default_rng(12)
    w.add(rng.standard_normal((24, DIM)).astype(np.float32))
    searchers = [serving.mutable_ivf_searcher(w) for _ in range(2)]
    cfg = serving.EngineConfig(max_batch=4, max_wait_us=2000,
                               warm_ks=(3,), warm_buckets=(1, 4))
    with serving.Fleet.from_searchers(
            searchers, engine_config=cfg,
            config=serving.FleetConfig(quorum=1)) as fleet:
        comp = mutable.Compactor(w, publish=fleet)
        assert comp.run_once("manual") == "ok"
        span = [s for s in w.span_sink.records
                if s["kind"] == "compaction"][-1]
        assert span["searcher_gen"] == [1, 1]  # every replica swapped
        d, i = fleet.search(rng.standard_normal(DIM).astype(np.float32), 3)
        assert np.asarray(i).shape == (3,)
    w.close()


# ------------------------------------------------------------ kill -9 suite


def _run_victim(directory, seed, mode, kill_after_acks):
    """Spawn the victim, SIGKILL it after ``kill_after_acks`` acked
    writes, and return the highest acked lsn."""
    child = os.path.join(os.path.dirname(__file__),
                         "_mutable_kill_child.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, child, directory, str(seed), mode],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=env)
    acked = 0
    try:
        for line in proc.stdout:
            if line.startswith("ACK"):
                acked = int(line.split()[1])
                if acked >= kill_after_acks:
                    break
            elif line.startswith("DONE"):
                break
        os.kill(proc.pid, signal.SIGKILL)
    finally:
        proc.wait(timeout=30)
    assert acked > 0, "victim never acknowledged a write"
    return acked


def _assert_recovered_matches_oracle(directory, seed, acked, tmp_path):
    """The recovered writer's applied prefix covers every ack and is
    bit-identical to a never-crashed writer fed the same prefix."""
    w = mutable.MutableIvf(directory, dim=CHILD_DIM,
                           registry=obs_metrics.Registry(),
                           group_window_s=0.0)
    rec = w.recovery
    assert rec["status"] in ("ok", "torn_tail")  # typed, never untyped
    if rec["status"] == "torn_tail":
        assert isinstance(rec["error"], IntegrityError)
        assert rec["error"].reason == "torn_tail"
    applied = w.applied_lsn
    assert applied >= acked, (
        f"lost acknowledged writes: acked lsn {acked}, recovered "
        f"applied_lsn {applied}")

    oracle = mutable.MutableIvf(str(tmp_path / "oracle"), dim=CHILD_DIM,
                                registry=obs_metrics.Registry(),
                                group_window_s=0.0)
    for op in make_ops(seed)[:applied]:
        apply_op(oracle, op)
    got_ids, got_vecs = _live_state(w)
    want_ids, want_vecs = _live_state(oracle)
    np.testing.assert_array_equal(got_ids, want_ids)
    np.testing.assert_array_equal(got_vecs, want_vecs)  # bit-identical
    w.close()
    oracle.close()
    return applied


def test_kill9_mid_append_recovers_every_acked_write(tmp_path):
    directory = str(tmp_path / "victim")
    acked = _run_victim(directory, seed=101, mode="plain",
                        kill_after_acks=20)
    _assert_recovered_matches_oracle(directory, 101, acked, tmp_path)


def test_kill9_mid_compaction_state_bit_identical(tmp_path):
    """Kill -9 lands while an aggressive compactor races the write
    stream (mid-build / mid-checkpoint / mid-trim windows). Recovery
    must land on exactly the applied prefix — checkpoint + WAL tail —
    bit-identical to a never-crashed all-delta writer."""
    directory = str(tmp_path / "victim")
    acked = _run_victim(directory, seed=202, mode="compact",
                        kill_after_acks=30)
    _assert_recovered_matches_oracle(directory, 202, acked, tmp_path)


def test_crash_mid_publish_recovers_and_republises(tmp_path):
    """The widest window: checkpoint durable, publish never happened
    (crash_compactor). The run fails typed; a recovery sees the
    checkpointed state; the next compaction publishes cleanly."""
    from raft_tpu import serving

    w = _writer(tmp_path)
    rng = np.random.default_rng(13)
    vecs = rng.standard_normal((24, DIM)).astype(np.float32)
    with _mutable_engine(w) as eng:
        eng.writer().add(vecs)
        comp = mutable.Compactor(w, publish=eng)
        with faults.crash_compactor(eng):
            assert comp.run_once("manual") == "failed"
        assert isinstance(comp.last_error, mutable.CompactorCrashed)
        assert eng.searcher_generation == 0  # publish never happened
        pre = _live_state(w)
    w.close()

    # simulated restart: the checkpoint the crashed run wrote restores
    w2 = mutable.MutableIvf(str(tmp_path / "idx"),
                            registry=obs_metrics.Registry(),
                            span_sink=obs_spans.ListSink(),
                            group_window_s=0.0)
    assert w2.recovery["status"] == "ok"
    got = _live_state(w2)
    np.testing.assert_array_equal(pre[0], got[0])
    np.testing.assert_array_equal(pre[1], got[1])
    with _mutable_engine(w2) as eng2:
        comp2 = mutable.Compactor(w2, publish=eng2)
        assert comp2.run_once("manual") in ("ok", "skipped")
        d, i = eng2.submit(vecs[3], 3).result(timeout=60)
        assert int(np.asarray(i).ravel()[0]) == 3
    w2.close()


# --------------------------------------------------------- verification


def test_verify_dir_classification(tmp_path):
    w = _writer(tmp_path)
    w.add(np.ones((4, DIM), np.float32))
    w.sync()
    directory = str(tmp_path / "idx")
    assert mutable.verify_dir(directory)["status"] == "ok"
    faults.tear_wal_tail(w, mode="truncate")
    w.close()
    report = mutable.verify_dir(directory)
    assert report["status"] == "torn_tail"
    assert report["wal"]["status"] == "torn_tail"

    # recovery repairs; a checkpoint makes the replay range empty
    w2 = _writer(tmp_path)
    w2.add(np.ones((2, DIM), np.float32))
    w2.close()
    report = mutable.verify_dir(directory)
    assert report["status"] == "ok"
    assert report["replay"]["records"] == report["wal"]["records"]

    faults.flip_record_byte(os.path.join(directory, "wal.log"), 0)
    # damage followed by live bytes classifies corrupt when more records
    # follow; with a single record it is a torn tail — either way typed
    report = mutable.verify_dir(directory)
    assert report["status"] in ("torn_tail", "corrupt")


def test_verify_checkpoint_tool_exit_codes(tmp_path):
    tool = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "verify_checkpoint.py")
    directory = str(tmp_path / "idx")
    w = _writer(tmp_path)
    w.add(np.ones((6, DIM), np.float32))
    w.close()
    env = dict(os.environ, JAX_PLATFORMS="cpu")

    r = subprocess.run([sys.executable, tool, directory],
                       capture_output=True, text=True, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "replay: lsn 1...1" in r.stdout

    faults.tear_wal_tail(os.path.join(directory, "wal.log"),
                         mode="truncate")
    r = subprocess.run([sys.executable, tool, directory],
                       capture_output=True, text=True, env=env)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "DEGRADED" in r.stdout

    with open(os.path.join(directory, "wal.log"), "wb") as f:
        f.write(b"garbage")
    r = subprocess.run([sys.executable, tool, directory],
                       capture_output=True, text=True, env=env)
    assert r.returncode == 2, r.stdout + r.stderr


# ------------------------------------------------- amplified interleaving


def _interleave_round(tmp_path, seed, n_ops=6):
    """One amplified seed: 2 writer threads on disjoint id ranges + a
    searcher + an aggressive compactor, then exact reconciliation of
    final state AND counters against the deterministic per-thread
    streams."""
    reg = obs_metrics.Registry()
    sink = obs_spans.ListSink()
    w = mutable.MutableIvf(str(tmp_path / f"s{seed}"), dim=4,
                           registry=reg, span_sink=sink,
                           group_window_s=0.0, name=f"s{seed}")
    comp = mutable.Compactor(w, delta_threshold=4, poll_s=0.002,
                             min_rows=1)
    expect: dict = {}
    errors: list = []

    def writer_thread(tid):
        rng = np.random.RandomState(seed * 31 + tid)
        base_id = tid * 1000
        try:
            for i in range(n_ops):
                id_ = base_id + i
                vec = rng.randn(1, 4).astype(np.float32)
                w.upsert(vec, [id_])
                expect[id_] = vec[0]
            w.delete([base_id])  # each thread deletes its first id
            del expect[base_id]
        except (RaftError, ValueError) as e:  # pragma: no cover
            errors.append(e)

    def searcher_thread():
        rng = np.random.RandomState(seed)
        try:
            for _ in range(3):
                q = rng.randn(1, 4).astype(np.float32)
                d, i = w.search(q, 3)
                ids = np.asarray(i).ravel()
                assert len(set(ids[ids >= 0].tolist())) == \
                    len(ids[ids >= 0]), "duplicate ids in one result row"
        except (RaftError, ValueError) as e:  # pragma: no cover
            errors.append(e)

    with InterleaveAmplifier(
            seed=seed, path_filters=("neighbors/mutable.py",)):
        comp.start()
        threads = [threading.Thread(target=writer_thread, args=(t,))
                   for t in range(2)]
        threads.append(threading.Thread(target=searcher_thread))
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        comp.stop()
    assert not errors, errors

    # exact final state: every thread's last write per id, minus deletes
    ids, vecs = _live_state(w)
    assert list(ids) == sorted(expect)
    for id_, vec in zip(ids, vecs):
        np.testing.assert_array_equal(vec, expect[int(id_)])

    # exact counter reconciliation for this seed's registry
    n_writes = 2 * (n_ops + 1)  # n_ops upserts + 1 delete per thread
    writes = sum(child.value for _, child in reg.get(
        "raft_tpu_mutable_writes_total").collect())
    acks = dict(reg.get("raft_tpu_mutable_acks_total").collect())[
        (w.name,)].value
    assert writes == n_writes
    assert acks == n_writes  # every write acked — none stalled
    comp_spans = [s for s in sink.records if s["kind"] == "compaction"]
    fam = reg.get("raft_tpu_mutable_compactions_total")
    counted = sum(child.value for _, child in fam.collect())
    assert counted == len(comp_spans)  # spans 1:1 with counters
    assert w.applied_lsn == n_writes
    w.close()


def test_mutable_interleave_fast_twin(tmp_path):
    """Tier-1 shape check of the amplified suite (3 seeds)."""
    for seed in seeds(3):
        _interleave_round(tmp_path, seed)


@pytest.mark.slow
@pytest.mark.interleave
def test_mutable_interleave_100_seeds(tmp_path):
    """The full 100-seed amplified sweep: concurrent writers +
    searchers + compactor with exact state and counter reconciliation
    on every seed (replay a failure via RAFT_TPU_INTERLEAVE_SEED)."""
    for seed in seeds(100):
        _interleave_round(tmp_path, seed)
