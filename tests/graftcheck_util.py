"""Shared fixture-twin plumbing for the graftcheck suites.

Every tier's tests do the same four things: load a one-violation
fixture twin from ``tests/data/graftcheck``, assert the bad twin is
caught and the clean twin is silent, check an inline ``# graftcheck:
RXXX`` suppression is honored, and drive ``tools/graftcheck.py`` as a
subprocess against an injected violation. This module is that
boilerplate, factored once; the tier suites keep only what is specific
to their rules.

A *runner* here is any callable ``(ModuleInfo) -> List[Finding]`` —
for one-argument rules pass the rule itself, for context-taking rules
(Tier F) pass a lambda that builds the context per module.
"""

import os
import subprocess
import sys
from typing import Optional

from raft_tpu.analysis import ModuleInfo

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXDIR = os.path.join(REPO, "tests", "data", "graftcheck")


def fixture_src(fname: str) -> str:
    with open(os.path.join(FIXDIR, fname)) as f:
        return f.read()


def fixture_mod(fname: str, modname: Optional[str] = None) -> ModuleInfo:
    """A fixture twin as a ModuleInfo, under the conventional
    ``raft_tpu.fixture_pkg_b`` modname unless the rule is scoped."""
    return ModuleInfo(os.path.join(FIXDIR, fname),
                      f"tests/data/graftcheck/{fname}",
                      modname or f"raft_tpu.fixture_pkg_b.{fname[:-3]}")


def tmp_mod(tmp_path, name: str, src: str,
            modname: Optional[str] = None) -> ModuleInfo:
    """Write ``src`` under ``tmp_path`` and parse it as a ModuleInfo."""
    p = tmp_path / name
    p.write_text(src)
    return ModuleInfo(str(p), name,
                      modname or f"raft_tpu.fixture.{name[:-3]}")


def check_twin(runner, rule_id: str, stem: str, expect_qual: str) -> None:
    """The twin contract: ``{stem}_bad.py`` yields exactly one finding
    of ``rule_id`` at ``expect_qual``; ``{stem}_clean.py`` is silent."""
    found = runner(fixture_mod(f"{stem}_bad.py"))
    assert [(f.rule, f.qualname) for f in found] == [(rule_id, expect_qual)], \
        [f.format() for f in found]
    clean = runner(fixture_mod(f"{stem}_clean.py"))
    assert clean == [], [f.format() for f in clean]


def check_suppression(runner, tmp_path, fname: str, anchor: str,
                      rule_id: str, modname: Optional[str] = None) -> None:
    """Appending ``# graftcheck: {rule_id}`` to the line containing
    ``anchor`` silences the bad twin's finding."""
    src = fixture_src(fname)
    assert anchor in src, (fname, anchor)
    src = src.replace(anchor, f"{anchor}  # graftcheck: {rule_id}", 1)
    mod = tmp_mod(tmp_path, fname.replace(".py", "_supp.py"), src, modname)
    found = runner(mod)
    assert found == [], [f.format() for f in found]


def run_cli(*args, cwd=None):
    """``tools/graftcheck.py`` as CI runs it; returns CompletedProcess."""
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "graftcheck.py"),
         *args],
        capture_output=True, text=True, cwd=cwd)


def inject(tmp_path, fname: str, subdir: str = "raft_tpu",
           as_name: str = "injected.py") -> str:
    """Copy a bad twin into a scratch tree for CLI gate tests; returns
    the scratch root."""
    pkg = tmp_path
    for part in subdir.split("/"):
        pkg = pkg / part
    pkg.mkdir(parents=True, exist_ok=True)
    (pkg / as_name).write_text(fixture_src(fname))
    return str(tmp_path)
