"""Telemetry reconciliation for the serving engine (docs/observability.md).

The contract under test: EVERY submit() is traced — each request-kind
span carries the trace id minted at submission and a typed outcome, and
the span file reconciles 1:1 with the registry counters for every
outcome, including the ones the chaos injectors force (batch failure,
hang, deadline shed, watermark and breaker rejections). Zero untraced
requests, zero phantom spans.

Shares the chaos suite's fixtures/idioms (tests/test_serving_chaos.py);
the same ~0.2-0.5 s warmed-search timing note applies to every
``hang_timeout_s`` choice here."""

import collections
import json
import urllib.request

import numpy as np
import pytest

from raft_tpu import serving
from raft_tpu.neighbors import ivf_flat
from raft_tpu.obs import metrics as obm
from raft_tpu.obs.spans import ListSink
from raft_tpu.testing import faults

pytestmark = pytest.mark.fast

DIM = 16
K = 5

#: outcome vocabulary → the stats counter each span outcome must match
OUTCOME_COUNTERS = {
    "ok": "n_completed",
    "cancelled": "n_cancelled",
    "shed_deadline": "n_shed_deadline",
    "rejected_overload": "n_rejected_overload",
    "rejected_breaker": "n_rejected_breaker",
}


@pytest.fixture(scope="module")
def flat_index():
    rng = np.random.default_rng(7)
    db = rng.standard_normal((1500, DIM)).astype(np.float32)
    return ivf_flat.build(db, ivf_flat.IndexParams(n_lists=16))


@pytest.fixture()
def searcher(flat_index):
    return serving.ivf_flat_searcher(flat_index,
                                     ivf_flat.SearchParams(n_probes=8))


def _engine(s, sink=None, **kw):
    kw.setdefault("max_batch", 8)
    kw.setdefault("max_wait_us", 5000)
    kw.setdefault("warm_ks", (K,))
    kw.setdefault("span_sink", sink)
    return serving.Engine(s, serving.EngineConfig(**kw))


def _q(rng):
    return rng.standard_normal(DIM).astype(np.float32)


def _reconcile(sink, stats):
    """Assert span outcomes match the registry counters 1:1; returns the
    per-outcome span tally. ``batch_failed`` and ``hang`` both count as
    ``n_failed`` (the hang verdict belongs to the watchdog)."""
    tally = collections.Counter(
        r["outcome"] for r in sink.by_kind("request"))
    for outcome, attr in OUTCOME_COUNTERS.items():
        assert tally.get(outcome, 0) == getattr(stats, attr), (
            outcome, dict(tally))
    assert (tally.get("batch_failed", 0) + tally.get("hang", 0)
            == stats.n_failed), dict(tally)
    return tally


# -------------------------------------------------------- the happy path

def test_every_completed_request_has_a_full_span(searcher):
    rng = np.random.default_rng(0)
    sink = ListSink()
    with _engine(searcher, sink, hang_timeout_s=None) as eng:
        futs = [eng.submit(_q(rng), K) for _ in range(10)]
        ids = set()
        for f in futs:
            f.result(timeout=60)
            ids.add(f.trace_id)
        eng.drain(60)
        assert len(ids) == 10  # every future carries a distinct trace id

        spans = sink.by_kind("request")
        assert {s["trace_id"] for s in spans} == ids  # zero untraced
        for s in spans:
            assert s["outcome"] == "ok"
            assert s["engine"] == eng.stats.engine_label
            # full phase decomposition + batch breadcrumbs
            for key in ("admission_ms", "queue_ms", "pad_copy_ms",
                        "device_ms", "readback_ms", "total_ms",
                        "batch_id", "bucket", "batch_size",
                        "searcher_gen", "coverage"):
                assert key in s, key
            assert s["total_ms"] >= 0 and s["coverage"] == 1.0
            assert s["searcher_gen"] == 0

        # batch records join back to every rider's trace id
        batch_ids = [t for b in sink.by_kind("batch")
                     for t in b["trace_ids"]]
        assert set(batch_ids) == ids and len(batch_ids) == 10
        assert all(b["outcome"] == "ok" for b in sink.by_kind("batch"))
        _reconcile(sink, eng.stats)


def test_span_records_are_json_serializable(searcher):
    rng = np.random.default_rng(1)
    sink = ListSink()
    with _engine(searcher, sink, hang_timeout_s=None) as eng:
        eng.search(_q(rng), K)
        eng.drain(60)
    for rec in sink.records:
        json.dumps(rec)  # the JSONL interchange contract


# ------------------------------------------------- chaos reconciliation

def test_batch_failure_and_shed_spans_reconcile(searcher):
    rng = np.random.default_rng(2)
    sink = ListSink()
    with _engine(searcher, sink, hang_timeout_s=None) as eng:
        # one poisoned batch
        faults.fail_next_dispatch(searcher)
        victim = eng.submit(_q(rng), K)
        with pytest.raises(serving.BatchFailed):
            victim.result(timeout=60)
        # a deadline shed: generous flush deadline, microscopic request
        # deadline — the batcher prunes it before any launch
        shed = eng.submit(_q(rng), K, deadline_ms=0.0)
        with pytest.raises(serving.DeadlineExceeded):
            shed.result(timeout=60)
        # healthy traffic after both incidents
        oks = [eng.submit(_q(rng), K) for _ in range(6)]
        for f in oks:
            f.result(timeout=60)
        eng.drain(60)

        tally = _reconcile(sink, eng.stats)
        assert tally["batch_failed"] == 1
        assert tally["shed_deadline"] == 1
        assert tally["ok"] == 6
        # the failed request's span carries the typed error + trace id
        (failed,) = [s for s in sink.by_kind("request")
                     if s["outcome"] == "batch_failed"]
        assert failed["trace_id"] == victim.trace_id
        assert "BatchFailed" in failed["error"]
        (shed_span,) = [s for s in sink.by_kind("request")
                        if s["outcome"] == "shed_deadline"]
        assert shed_span["trace_id"] == shed.trace_id
        assert shed_span["shed_after_ms"] >= 0.0
        # the failed batch record is typed too
        bad_batches = [b for b in sink.by_kind("batch")
                       if b["outcome"] == "batch_failed"]
        assert len(bad_batches) == 1
        assert bad_batches[0]["trace_ids"] == [victim.trace_id]


def test_hang_and_breaker_rejection_spans_reconcile(searcher):
    rng = np.random.default_rng(3)
    sink = ListSink()
    with _engine(searcher, sink, hang_timeout_s=1.0,
                 breaker_cooldown_s=30.0, max_wait_us=0) as eng:
        faults.hang_next_dispatch(searcher, hang_s=3.0)
        victim = eng.submit(_q(rng), K)
        with pytest.raises(serving.BatchFailed) as ei:
            victim.result(timeout=60)
        assert ei.value.hang is True
        # breaker is now open: admission rejects, and the rejection is
        # itself traced (rejections never enter the queue)
        with pytest.raises(serving.CircuitOpen):
            eng.submit(_q(rng), K)
        eng.drain(60)

        tally = _reconcile(sink, eng.stats)
        assert tally["hang"] == 1
        assert tally["rejected_breaker"] == 1
        (rej,) = [s for s in sink.by_kind("request")
                  if s["outcome"] == "rejected_breaker"]
        assert "CircuitOpen" in rej["error"]
        assert len(rej["trace_id"]) == 16


def test_overload_rejection_spans_reconcile(searcher):
    rng = np.random.default_rng(4)
    sink = ListSink()
    # tiny watermark + an enormous flush deadline so the queue backs up
    eng = _engine(searcher, sink, hang_timeout_s=None, max_wait_us=int(5e7),
                  queue_high_watermark=2, queue_low_watermark=1)
    with eng:
        admitted, rejected = [], 0
        for _ in range(6):
            try:
                admitted.append(eng.submit(_q(rng), K))
            except serving.Overloaded:
                rejected += 1
        assert rejected >= 1 and admitted
        eng.stop(drain=True)  # void flush deadlines, launch the queue
        for f in admitted:
            f.result(timeout=60)

        tally = _reconcile(sink, eng.stats)
        assert tally["rejected_overload"] == rejected
        assert tally["ok"] == len(admitted)


def test_cancelled_on_stop_is_traced(searcher):
    rng = np.random.default_rng(5)
    sink = ListSink()
    eng = _engine(searcher, sink, hang_timeout_s=None, max_wait_us=int(5e7))
    with eng:
        futs = [eng.submit(_q(rng), K) for _ in range(3)]
        eng.stop(drain=False)  # queued requests are cancelled
        tally = _reconcile(sink, eng.stats)
        assert tally["cancelled"] == 3
        cancelled = [s for s in sink.by_kind("request")
                     if s["outcome"] == "cancelled"]
        assert {s["trace_id"] for s in cancelled} == \
            {f.trace_id for f in futs}
        assert all(s["where"] == "stop" for s in cancelled)


def test_swap_emits_generation_span(searcher, flat_index):
    rng = np.random.default_rng(6)
    sink = ListSink()
    other = serving.ivf_flat_searcher(flat_index,
                                      ivf_flat.SearchParams(n_probes=8))
    with _engine(searcher, sink, hang_timeout_s=None) as eng:
        eng.search(_q(rng), K)
        eng.swap_index(other)
        d, i = eng.search(_q(rng), K)
        assert d.shape == (K,)
        eng.drain(60)
        (swap,) = sink.by_kind("swap")
        assert swap["searcher_gen"] == 1
        assert swap["old_coverage"] == swap["new_coverage"] == 1.0
        # post-swap requests carry the new generation breadcrumb
        gens = {s["searcher_gen"] for s in sink.by_kind("request")}
        assert gens == {0, 1}


# ------------------------------------------------- scrape + warm start

def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=5) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def test_metrics_endpoint_on_running_engine(searcher):
    rng = np.random.default_rng(8)
    # default (global) registry so the scrape includes the process-wide
    # compile counter next to this engine's families
    with _engine(searcher, hang_timeout_s=None, metrics_port=0) as eng:
        assert eng.metrics_server is not None
        url = eng.metrics_server.url
        for _ in range(4):
            eng.search(_q(rng), K)
        eng.drain(60)

        code, body = _get(url + "/healthz")
        assert code == 200 and json.loads(body)["status"] == "ok"

        code, text = _get(url + "/metrics")
        assert code == 200
        e = eng.stats.engine_label
        # request counters incl. the pre-touched shed/reject children
        assert (f'raft_tpu_serving_requests_total{{engine="{e}",'
                f'event="completed"}} 4') in text
        for ev in ("rejected_overload", "rejected_breaker",
                   "shed_deadline"):
            assert (f'raft_tpu_serving_requests_total{{engine="{e}",'
                    f'event="{ev}"}} 0') in text
        # latency histogram buckets, compile counter, autoscale gauge
        assert f'raft_tpu_serving_queue_wait_seconds_bucket{{engine="{e}"' \
            in text
        assert "raft_tpu_xla_compile_total" in text
        assert f'raft_tpu_serving_autoscale_pressure{{engine="{e}"}}' \
            in text
        assert f'raft_tpu_serving_queue_depth{{engine="{e}"}} 0' in text

        code, body = _get(url + "/metrics.json")
        assert code == 200
        doc = json.loads(body)
        series = doc["raft_tpu_serving_requests_total"]["series"]
        completed = [s for s in series
                     if s["labels"] == {"engine": e, "event": "completed"}]
        assert completed[0]["value"] == 4.0
    # engine stop tears the server down
    with pytest.raises(OSError):
        urllib.request.urlopen(url + "/healthz", timeout=1)


def test_healthz_degrades_and_recovers_with_breaker(searcher):
    rng = np.random.default_rng(9)
    with _engine(searcher, hang_timeout_s=1.0, breaker_cooldown_s=30.0,
                 max_wait_us=0, metrics_port=0) as eng:
        url = eng.metrics_server.url
        assert _get(url + "/healthz")[0] == 200
        faults.hang_next_dispatch(searcher, hang_s=3.0)
        with pytest.raises(serving.BatchFailed):
            eng.submit(_q(rng), K).result(timeout=60)
        code, body = _get(url + "/healthz")  # breaker open → 503
        assert code == 503
        assert json.loads(body)["breaker"] == "open"
        eng.drain(60)


def test_warm_start_still_precompiles_with_telemetry_enabled(searcher):
    rng = np.random.default_rng(10)
    sink = ListSink()
    with _engine(searcher, sink, hang_timeout_s=None) as eng:
        # (warmup_info["compiles"] may be 0 here: earlier tests in this
        # process already compiled these shapes; the delta is what counts)
        assert "compiles" in eng.warmup_info
        c0 = serving.compile_count()
        for _ in range(5):
            eng.search(_q(rng), K)
        eng.drain(60)
        # telemetry must not perturb the warmed shapes: zero compiles
        # after start() on the instrumented path
        assert serving.compile_count() == c0


def test_autoscale_pressure_gauge_derives_from_registry(searcher):
    rng = np.random.default_rng(11)
    reg = obm.Registry()
    with _engine(searcher, hang_timeout_s=None, registry=reg,
                 deadline_budget_ms=50.0) as eng:
        gauge = reg.get("raft_tpu_serving_autoscale_pressure")
        child = gauge.labels(eng.stats.engine_label)
        assert child.value == 0.0  # no batches yet → no queue-wait p99
        for _ in range(6):
            eng.search(_q(rng), K)
        eng.drain(60)
        expected = eng.stats.queue_wait_p99_s() * 1e3 / 50.0
        assert child.value == pytest.approx(expected)
        assert 0.0 <= child.value < 1e6
