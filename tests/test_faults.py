"""Chaos tests: inject real faults (byte flips, lost files, severed
sockets, shrunken memory budgets) and require the serving stack to either
degrade gracefully or fail with a typed, actionable error — never hang,
never serve silently-wrong results. Runs entirely on the 8-device virtual
CPU mesh."""

import shutil
import socket
import struct
import threading
import time

import numpy as np
import pytest

from raft_tpu.core.errors import IntegrityError
from raft_tpu.core.resources import Resources
from raft_tpu.neighbors import ivf_flat, ivf_pq
from raft_tpu.parallel import comms as comms_mod
from raft_tpu.parallel import sharded
from raft_tpu.parallel.host_p2p import _HDR, _MAGIC, HostP2P
from raft_tpu.testing import faults

N_ROWS, DIM, N_SHARDS = 4096, 32, 8


@pytest.fixture(scope="module")
def pq_checkpoint(tmp_path_factory):
    """One sharded IVF-PQ build + checkpoint, copied per test before any
    fault is injected (rows split 512/shard, so losing one shard is
    exactly 1/8 of coverage)."""
    rng = np.random.default_rng(7)
    centers = (rng.standard_normal((32, DIM)) * 4).astype(np.float32)
    x = (centers[rng.integers(0, 32, N_ROWS)]
         + rng.standard_normal((N_ROWS, DIM))).astype(np.float32)
    q = (centers[rng.integers(0, 32, 16)]
         + rng.standard_normal((16, DIM))).astype(np.float32)
    comms = comms_mod.init_comms(axis="faults_pq")
    idx = sharded.build_ivf_pq(
        comms, x, ivf_pq.IndexParams(n_lists=16, pq_dim=16,
                                     kmeans_n_iters=3),
        res=Resources(seed=0), scan_mode="lut")
    d = tmp_path_factory.mktemp("pq_ckpt")
    sharded.serialize_ivf_pq(idx, str(d / "idx"))
    return d, q


@pytest.fixture()
def pq_prefix(pq_checkpoint, tmp_path):
    src, q = pq_checkpoint
    for p in src.iterdir():
        shutil.copy(p, tmp_path / p.name)
    return str(tmp_path / "idx"), q


def _elastic_subset(el, ranks):
    """An ElasticIvfPq over a hand-picked subset of a FULL restore's
    shards — the ground truth a degraded restore must match bit-for-bit."""
    sel = np.asarray(ranks)

    def tk(a):
        return None if a is None else np.asarray(a)[sel]

    return sharded.ElasticIvfPq(
        len(ranks), tk(el.centers), tk(el.rotation), tk(el.list_indices),
        tk(el.list_sizes), el.metric, el.n_rows,
        list_decoded=tk(el.list_decoded),
        decoded_norms=tk(el.decoded_norms), codebooks=tk(el.codebooks),
        list_codes=tk(el.list_codes), per_cluster=el.per_cluster,
        pq_dim=el.pq_dim, pq_bits=el.pq_bits,
        overflow_decoded=tk(el.overflow_decoded),
        overflow_norms=tk(el.overflow_norms),
        overflow_indices=tk(el.overflow_indices))


# --------------------------------------------------- checkpoint integrity


def test_delete_rank_degraded_restore(pq_prefix):
    """Acceptance (a): losing 1 of 8 rank files -> allow_partial restore
    with coverage exactly 7/8, searching only surviving shards
    bit-identically to a full restore restricted to the same shards;
    strict restore names the missing path."""
    prefix, q = pq_prefix
    el_full = sharded.deserialize_ivf_pq_elastic(prefix)
    assert el_full.coverage == 1.0

    dead = 3
    gone = faults.delete_rank_file(prefix, dead)
    with pytest.raises(ValueError, match=r"missing \[3\]") as ei:
        sharded.deserialize_ivf_pq_elastic(prefix)
    assert f"idx.rank{dead}" in str(ei.value)

    el = sharded.deserialize_ivf_pq_elastic(prefix, allow_partial=True)
    assert el.coverage == (N_SHARDS - 1) / N_SHARDS
    assert el.n_shards == N_SHARDS - 1
    assert el.shard_ranks == [r for r in range(N_SHARDS) if r != dead]

    sp = ivf_pq.SearchParams(n_probes=8)
    result = el.search(q, 10, sp)
    d1, i1 = result  # still unpacks as a 2-tuple
    assert result.coverage == el.coverage

    # bit-identity vs the full restore restricted to the same shards
    d2, i2 = _elastic_subset(el_full, el.shard_ranks).search(q, 10, sp)
    np.testing.assert_array_equal(np.asarray(d1), np.asarray(d2))
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    # no id from the dead shard's rows (rows split evenly -> contiguous)
    ids = np.asarray(i1)
    lo, hi = dead * (N_ROWS // N_SHARDS), (dead + 1) * (N_ROWS // N_SHARDS)
    assert not np.any((ids >= lo) & (ids < hi)), gone


def test_flip_byte_typed_integrity_error(pq_prefix):
    """Acceptance (b): one flipped payload byte -> IntegrityError naming
    the file and the record; degraded restore routes around it."""
    prefix, q = pq_prefix
    bad = f"{prefix}.rank2"
    # record 6 is past the header scalars, inside the field payloads
    faults.flip_record_byte(bad, 6, offset=5)
    with pytest.raises(IntegrityError) as ei:
        sharded.deserialize_ivf_pq_elastic(prefix)
    assert ei.value.reason == "corrupt"
    assert ei.value.path == bad
    assert ei.value.record == 6

    el = sharded.deserialize_ivf_pq_elastic(prefix, allow_partial=True)
    assert el.coverage == (N_SHARDS - 1) / N_SHARDS
    assert 2 not in el.shard_ranks
    d, i = el.search(q, 10, ivf_pq.SearchParams(n_probes=8))
    assert np.asarray(i).shape == (len(q), 10)


def test_truncated_rank_file(pq_prefix):
    prefix, _ = pq_prefix
    bad = f"{prefix}.rank5"
    faults.truncate_record(bad, 4)
    with pytest.raises(IntegrityError) as ei:
        sharded.deserialize_ivf_pq_elastic(prefix)
    assert ei.value.reason == "truncated"
    assert ei.value.path == bad
    el = sharded.deserialize_ivf_pq_elastic(prefix, allow_partial=True)
    assert 5 not in el.shard_ranks


def test_footer_detects_silent_tail_truncation(pq_prefix):
    """Cutting the footer off (no record torn) must still read as
    truncated — a file can otherwise lose its tail records silently."""
    prefix, _ = pq_prefix
    bad = f"{prefix}.rank0"
    faults.truncate_file(bad, drop_bytes=4)
    with pytest.raises(IntegrityError) as ei:
        sharded.deserialize_ivf_pq_elastic(prefix)
    assert ei.value.reason == "truncated"


def test_verify_checkpoint_classifies(pq_prefix):
    """The pre-flight tool (TPU runbook) classifies every fault class
    without reading payloads into memory."""
    prefix, _ = pq_prefix
    rep = sharded.verify_checkpoint(prefix)
    assert rep["ok"] and not rep["missing_ranks"]
    assert rep["size"] == N_SHARDS
    assert all(s == "ok" for s in rep["files"].values())

    faults.delete_rank_file(prefix, 0)
    faults.truncate_record(f"{prefix}.rank1", 3)
    faults.flip_record_byte(f"{prefix}.rank2", 2)
    rep = sharded.verify_checkpoint(prefix)
    assert not rep["ok"]
    assert rep["files"]["idx.rank0"] == "missing"
    assert rep["files"]["idx.rank1"] == "truncated"
    assert rep["files"]["idx.rank2"] == "corrupt"
    assert rep["missing_ranks"] == [0, 1, 2]
    assert rep["coverage_ranks"] == [3, 4, 5, 6, 7]


def test_ivf_flat_elastic_degraded(tmp_path):
    """The IVF-Flat twin: same delete-one-shard contract."""
    rng = np.random.default_rng(11)
    x = rng.standard_normal((N_ROWS, 16)).astype(np.float32)
    q = x[:8] + 0.01 * rng.standard_normal((8, 16)).astype(np.float32)
    comms = comms_mod.init_comms(axis="faults_flat")
    idx = sharded.build_ivf_flat(
        comms, x, ivf_flat.IndexParams(n_lists=16, kmeans_n_iters=2),
        res=Resources(seed=0))
    prefix = str(tmp_path / "flat")
    sharded.serialize_ivf_flat(idx, prefix)

    el_full = sharded.deserialize_ivf_flat_elastic(prefix)
    assert el_full.coverage == 1.0
    d0, i0 = el_full.search(q, 10, ivf_flat.SearchParams(n_probes=16))
    faults.delete_rank_file(prefix, 6)
    with pytest.raises(ValueError, match=r"missing \[6\]"):
        sharded.deserialize_ivf_flat_elastic(prefix)
    el = sharded.deserialize_ivf_flat_elastic(prefix, allow_partial=True)
    assert el.coverage == (N_SHARDS - 1) / N_SHARDS
    res = el.search(q, 10, ivf_flat.SearchParams(n_probes=16))
    assert res.coverage == el.coverage
    ids = np.asarray(res.indices)
    lo, hi = 6 * (N_ROWS // N_SHARDS), 7 * (N_ROWS // N_SHARDS)
    assert not np.any((ids >= lo) & (ids < hi))
    # every result that did not come from the dead shard is unchanged
    keep = ~((np.asarray(i0) >= lo) & (np.asarray(i0) < hi))
    assert np.all(np.isin(np.asarray(i0)[keep], np.asarray(res.indices)))


# ------------------------------------------------------- host p2p faults


def _ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def test_sever_mid_stream_send_retries():
    """Acceptance (c): cut the live connection between two sends — the
    sender's retry/backoff re-delivers and waitall completes."""
    ports = _ports(2)
    peers = [("127.0.0.1", p) for p in ports]
    a = HostP2P(0, 2, peers=peers, timeout=30,
                retries=5, retry_backoff=0.02, retry_backoff_max=0.1)
    b = HostP2P(1, 2, peers=peers, timeout=30)
    try:
        a.isend(b"first", dest=1).wait(30)
        assert b.irecv(source=0).wait(30) == b"first"
        assert faults.sever_connection(a, 1)  # hard-cut the live socket
        reqs = [a.isend(f"m{i}".encode(), dest=1, tag=1) for i in range(4)]
        HostP2P.waitall(reqs, timeout=30)  # completes via retry, no poison
        got = [b.irecv(source=0, tag=1).wait(30) for _ in range(4)]
        # at-least-once: retry may duplicate the frame in flight when the
        # cut landed post-buffer; order within the stream is preserved
        assert got[0] == b"m0" and set(got) <= {b"m0", b"m1", b"m2", b"m3"}
    finally:
        a.close()
        b.close()


def test_retries_zero_restores_fail_fast():
    """retries=0 keeps the original poison-on-first-failure contract."""
    ports = _ports(2)
    peers = [("127.0.0.1", p) for p in ports]
    a = HostP2P(0, 2, peers=peers, timeout=5, retries=0)
    try:
        with pytest.raises(OSError):
            a.isend(b"x", dest=1).wait(10)  # nothing listens on port 1
        with pytest.raises(ConnectionError, match="poisoned"):
            a.isend(b"y", dest=1).wait(10)
    finally:
        a.close()


def test_unreachable_peer_wait_bounded():
    """Acceptance (c): wait(timeout=t) against an unreachable peer raises
    TimeoutError within 2t — for sends still retrying AND for receives
    whose message can never come; wait() with no timeout uses the
    endpoint's deadline instead of hanging."""
    ports = _ports(2)
    peers = [("127.0.0.1", p) for p in ports]
    a = HostP2P(0, 2, peers=peers, timeout=0.8,
                retries=1000, retry_backoff=0.2, retry_backoff_max=0.2)
    try:
        t = 1.0
        s = a.isend(b"x", dest=1)  # port 1 refuses; send keeps retrying
        t0 = time.monotonic()
        with pytest.raises(TimeoutError):
            s.wait(timeout=t)
        assert time.monotonic() - t0 < 2 * t

        r = a.irecv(source=1)
        t0 = time.monotonic()
        with pytest.raises(TimeoutError):
            r.wait(timeout=t)
        assert time.monotonic() - t0 < 2 * t

        r2 = a.irecv(source=1)
        t0 = time.monotonic()
        with pytest.raises(TimeoutError):
            r2.wait()  # no explicit timeout: endpoint timeout applies
        assert time.monotonic() - t0 < 2 * 0.8
    finally:
        a.close()


def test_peer_death_fails_pending_irecvs():
    """A connection cut MID-FRAME with no re-delivery within peer_grace
    fails every pending irecv from that source with ConnectionError —
    promptly, not after the full endpoint timeout."""
    ports = _ports(2)
    peers = [("127.0.0.1", p) for p in ports]
    ep = HostP2P(0, 2, peers=peers, timeout=60, peer_grace=0.3)
    try:
        raw = socket.create_connection(peers[0], timeout=5)
        # one whole frame first: establishes src=1 and bumps its
        # delivery generation
        payload = b"hello"
        raw.sendall(_HDR.pack(_MAGIC, 1, 0, len(payload)))
        raw.sendall(b"B")
        raw.sendall(payload)
        assert ep.irecv(source=1).wait(10) == b"hello"

        pending = [ep.irecv(source=1, tag=t) for t in (0, 1)]
        other_src = ep.irecv(source=0, tag=0)
        raw.sendall(_HDR.pack(_MAGIC, 1, 0, 999)[:7])  # cut mid-header
        raw.close()
        t0 = time.monotonic()
        for r in pending:
            with pytest.raises(ConnectionError, match="presumed dead"):
                r.wait(10)
        assert time.monotonic() - t0 < 5  # grace + slack, not timeout=60
        assert not other_src.done()  # unrelated source untouched
    finally:
        ep.close()


def test_reconnect_within_grace_voids_death_verdict():
    """A sender retry that reconnects inside the grace window proves the
    peer alive: pending irecvs must get the re-delivered message, not a
    death error."""
    ports = _ports(2)
    peers = [("127.0.0.1", p) for p in ports]
    ep = HostP2P(0, 2, peers=peers, timeout=60, peer_grace=0.5)
    try:
        raw = socket.create_connection(peers[0], timeout=5)
        raw.sendall(_HDR.pack(_MAGIC, 1, 0, 1))
        raw.sendall(b"B")
        raw.sendall(b"a")
        assert ep.irecv(source=1).wait(10) == b"a"
        pending = ep.irecv(source=1)
        raw.sendall(_HDR.pack(_MAGIC, 1, 0, 999)[:5])  # abnormal cut
        raw.close()
        # "retry": a fresh connection delivering within the grace window
        raw2 = socket.create_connection(peers[0], timeout=5)
        raw2.sendall(_HDR.pack(_MAGIC, 1, 0, 5))
        raw2.sendall(b"B")
        raw2.sendall(b"again")
        assert pending.wait(10) == b"again"
        time.sleep(0.8)  # outlive the grace timer: verdict must be void
        late = ep.irecv(source=1)
        raw2.sendall(_HDR.pack(_MAGIC, 1, 0, 4))
        raw2.sendall(b"B")
        raw2.sendall(b"more")
        assert late.wait(10) == b"more"
        raw2.close()
    finally:
        ep.close()


def test_mark_peer_dead_short_circuits():
    ports = _ports(2)
    peers = [("127.0.0.1", p) for p in ports]
    ep = HostP2P(0, 2, peers=peers, timeout=60)
    try:
        r = ep.irecv(source=1)
        ep.mark_peer_dead(1)
        with pytest.raises(ConnectionError, match="marked dead"):
            r.wait(5)
    finally:
        ep.close()


# -------------------------------------------------- build cancellation


def test_map_shards_cancels_siblings_on_failure(monkeypatch):
    """First shard-build failure cancels the siblings via
    core.interruptible instead of letting them run to completion."""
    from raft_tpu.core import interruptible

    monkeypatch.setenv("RAFT_TPU_PARALLEL_BUILD", "1")
    comms = comms_mod.init_comms(axis="faults_cancel")
    state = {"cancelled": 0, "completed": 0}
    lock = threading.Lock()

    def one(r, shard_res):
        if r == 0:
            return r  # the (serial) warm-up shard: instant
        if r == 1:
            time.sleep(0.2)  # let siblings enter their loops
            raise RuntimeError("shard build exploded")
        try:
            for _ in range(200):  # ~10s if never cancelled
                interruptible.yield_now()
                time.sleep(0.05)
        except interruptible.InterruptedException:
            with lock:
                state["cancelled"] += 1
            raise
        with lock:
            state["completed"] += 1
        return r

    t0 = time.monotonic()
    with pytest.raises(RuntimeError, match="shard build exploded"):
        # uniform spans -> exactly one (instant) warm-up shard, rank 0
        sharded._map_shards(comms, one, Resources(seed=0),
                            spans=[1] * comms.size)
    elapsed = time.monotonic() - t0
    # warm-up ranks (serial, pre-failure) complete; the parallel siblings
    # get cancelled long before their 10s of sleeping finishes
    assert state["cancelled"] >= 1
    assert elapsed < 8.0, elapsed


# ----------------------------------------------------- memory pressure


def test_workspace_shrink_same_results():
    """A 1 MiB workspace budget forces the tiled paths; results must not
    change (acceptance: memory pressure degrades speed, never answers)."""
    rng = np.random.default_rng(3)
    x = rng.standard_normal((2048, DIM)).astype(np.float32)
    q = x[:16] + 0.01 * rng.standard_normal((16, DIM)).astype(np.float32)
    res = Resources(seed=0)
    idx = ivf_pq.build(x, ivf_pq.IndexParams(n_lists=16, pq_dim=16,
                                             kmeans_n_iters=3), res=res)
    # pin the engine: the budget may only change TILING, not numerics
    sp = ivf_pq.SearchParams(n_probes=8, scan_mode="lut")
    d0, i0 = ivf_pq.search(idx, q, 10, sp, res=res)
    with faults.shrink_workspace(res, 1 << 20):
        assert res.workspace_limit_bytes == 1 << 20
        d1, i1 = ivf_pq.search(idx, q, 10, sp, res=res)
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
    np.testing.assert_allclose(np.asarray(d0), np.asarray(d1), rtol=1e-6)
