"""IVF-Flat tests — recall against exact brute-force ground truth, the
reference's acceptance pattern (cpp/test/neighbors/ann_ivf_flat.cuh:
build→(serialize→load)→search, assert recall ≥ floor)."""

import io

import numpy as np
import pytest
import jax.numpy as jnp

from raft_tpu import Resources
from raft_tpu.core.bitset import Bitset
from raft_tpu.neighbors import brute_force, ivf_flat
from raft_tpu.stats import neighborhood_recall


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(0)
    db = rng.standard_normal((5000, 32)).astype(np.float32)
    q = rng.standard_normal((100, 32)).astype(np.float32)
    return db, q


@pytest.fixture(scope="module")
def gt(data):
    db, q = data
    _, idx = brute_force.knn(q, db, k=10, metric="sqeuclidean")
    return np.asarray(idx)


def test_build_shapes(data):
    db, _ = data
    index = ivf_flat.build(db, ivf_flat.IndexParams(n_lists=32))
    assert index.n_lists == 32
    assert index.size == len(db)
    assert int(np.asarray(index.list_sizes).sum()) == len(db)
    # balanced lists
    sizes = np.asarray(index.list_sizes)
    assert sizes.max() <= 4 * len(db) / 32


@pytest.mark.parametrize("n_probes,floor", [(4, 0.4), (8, 0.6), (32, 0.999)])
@pytest.mark.slow
def test_recall_increases_with_probes(data, gt, n_probes, floor):
    db, q = data
    index = ivf_flat.build(db, ivf_flat.IndexParams(n_lists=32))
    d, i = ivf_flat.search(index, q, 10, ivf_flat.SearchParams(n_probes=n_probes))
    recall = float(neighborhood_recall(np.asarray(i), gt))
    assert recall >= floor, f"recall {recall} < {floor} at n_probes={n_probes}"


@pytest.mark.slow
def test_full_probe_is_exact(data, gt):
    db, q = data
    index = ivf_flat.build(db, ivf_flat.IndexParams(n_lists=16))
    d, i = ivf_flat.search(index, q, 10, ivf_flat.SearchParams(n_probes=16))
    assert float(neighborhood_recall(np.asarray(i), gt)) >= 0.999
    # distances match brute force
    bf_d, _ = brute_force.knn(q, db, k=10, metric="sqeuclidean")
    np.testing.assert_allclose(np.asarray(d), np.asarray(bf_d), rtol=1e-3, atol=1e-3)


def test_inner_product(data):
    db, q = data
    dbn = db / np.linalg.norm(db, axis=1, keepdims=True)
    index = ivf_flat.build(
        dbn, ivf_flat.IndexParams(n_lists=16, metric="inner_product"))
    d, i = ivf_flat.search(index, q, 10, ivf_flat.SearchParams(n_probes=16))
    ip = q @ dbn.T
    want = np.argsort(-ip, 1)[:, :10]
    assert float(neighborhood_recall(np.asarray(i), want)) >= 0.999


def test_extend_matches_single_shot_lists(data):
    """Device-side extend must place rows/ids exactly where a from-scratch
    pack of the same rows would (the ivf_flat analog of the ivf_pq gate)."""
    from raft_tpu.neighbors import ivf_flat as fl

    db, _ = data
    params = fl.IndexParams(n_lists=12, add_data_on_build=False)
    base = fl.build(db, params)
    one = fl.extend(base, db)
    half = len(db) // 2
    two = fl.extend(base, db[:half])
    two = fl.extend(two, db[half:])
    assert two.size == one.size == len(db)
    np.testing.assert_array_equal(np.asarray(one.list_sizes),
                                  np.asarray(two.list_sizes))
    np.testing.assert_array_equal(np.asarray(one.list_indices),
                                  np.asarray(two.list_indices))
    np.testing.assert_array_equal(np.asarray(one.list_data),
                                  np.asarray(two.list_data))


def test_extend(data, gt):
    db, q = data
    half = len(db) // 2
    index = ivf_flat.build(db[:half], ivf_flat.IndexParams(n_lists=32))
    index = ivf_flat.extend(index, db[half:])
    assert index.size == len(db)
    d, i = ivf_flat.search(index, q, 10, ivf_flat.SearchParams(n_probes=32))
    assert float(neighborhood_recall(np.asarray(i), gt)) >= 0.999


def test_build_no_data_then_extend(data, gt):
    db, q = data
    params = ivf_flat.IndexParams(n_lists=32, add_data_on_build=False)
    index = ivf_flat.build(db, params)
    with pytest.raises(ValueError, match="no data"):
        ivf_flat.search(index, q, 10)
    index = ivf_flat.extend(index, db)
    d, i = ivf_flat.search(index, q, 10, ivf_flat.SearchParams(n_probes=32))
    assert float(neighborhood_recall(np.asarray(i), gt)) >= 0.999


def test_bitset_filter(data):
    db, q = data
    index = ivf_flat.build(db, ivf_flat.IndexParams(n_lists=16))
    # forbid the true top-1 of each query
    _, bf_i = brute_force.knn(q, db, k=1, metric="sqeuclidean")
    banned = np.unique(np.asarray(bf_i).ravel())
    filt = Bitset.create(len(db)).set(banned, value=False)
    d, i = ivf_flat.search(index, q, 10, ivf_flat.SearchParams(n_probes=16),
                           filter=filt)
    got = np.asarray(i)
    assert not np.isin(got, banned).any()


def test_serialize_roundtrip(data, gt):
    db, q = data
    index = ivf_flat.build(db, ivf_flat.IndexParams(n_lists=32))
    buf = io.BytesIO()
    ivf_flat.serialize(index, buf)
    buf.seek(0)
    index2 = ivf_flat.deserialize(buf)
    d1, i1 = ivf_flat.search(index, q, 10, ivf_flat.SearchParams(n_probes=8))
    d2, i2 = ivf_flat.search(index2, q, 10, ivf_flat.SearchParams(n_probes=8))
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
    np.testing.assert_allclose(np.asarray(d1), np.asarray(d2), rtol=1e-6)


def test_small_workspace_tiles(data, gt):
    db, q = data
    index = ivf_flat.build(db, ivf_flat.IndexParams(n_lists=32))
    small = Resources(workspace_limit_bytes=8_000_000)
    d, i = ivf_flat.search(index, q, 10, ivf_flat.SearchParams(n_probes=32),
                           res=small)
    assert float(neighborhood_recall(np.asarray(i), gt)) >= 0.999


def test_helpers_pack_unpack(data):
    db, _ = data
    index = ivf_flat.build(db, ivf_flat.IndexParams(n_lists=16))
    vecs = ivf_flat.helpers.unpack_list_data(index, 2)
    ids = ivf_flat.helpers.unpack_list_ids(index, 2)
    assert len(vecs) == len(ids) == int(np.asarray(index.list_sizes)[2])
    np.testing.assert_allclose(vecs, db[ids], rtol=1e-6)
    # overwrite list 2 with its first 3 vectors
    idx2 = ivf_flat.helpers.pack_list_data(index, 2, vecs[:3], ids[:3])
    assert int(np.asarray(idx2.list_sizes)[2]) == 3
    np.testing.assert_allclose(ivf_flat.helpers.unpack_list_data(idx2, 2),
                               vecs[:3], rtol=1e-6)


def test_pallas_scan_path_matches_xla(data):
    db, q = data
    index = ivf_flat.build(db, ivf_flat.IndexParams(n_lists=16))
    empty = jnp.zeros((0,), jnp.uint32)
    args = (jnp.asarray(q[:16]), index.centers, index.list_data,
            index.list_indices, index.list_sizes, empty, index.metric,
            10, 8, 16, False)
    d1, i1 = ivf_flat._search_core(*args)
    d2, i2 = ivf_flat._search_core(
        *args, row_norms=index.ensure_row_norms(), use_pallas=True,
        pallas_interpret=True)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
    np.testing.assert_allclose(np.asarray(d1), np.asarray(d2),
                               rtol=1e-3, atol=1e-3)


@pytest.mark.parametrize("dt", [np.int8, np.uint8])
def test_int8_dataset(dt, rng):
    """int8/uint8 datasets (reference: ivf_flat's dp4a paths support
    int8/uint8 natively — ivf_flat_interleaved_scan-inl.cuh:99-251); storage
    stays narrow (4x less scan bandwidth), math is f32."""
    lo = -120 if dt == np.int8 else 0
    db = rng.integers(lo, 120, (2000, 32)).astype(dt)
    q = rng.integers(lo, 120, (100, 32)).astype(dt)
    idx = ivf_flat.build(db, ivf_flat.IndexParams(n_lists=16))
    assert idx.list_data.dtype == dt
    _, i = ivf_flat.search(idx, q, 5, ivf_flat.SearchParams(n_probes=16))
    ref = ((q.astype(np.float32)[:, None, :]
            - db.astype(np.float32)[None, :, :]) ** 2).sum(-1)
    np.testing.assert_array_equal(np.asarray(i)[:, 0], ref.argmin(1))


def test_bf16_fast_scan(data, gt):
    """bf16 fine scan with exact fp32 norms matches the fp32 scan's recall
    at full probing (all lists probed → only scan precision differs)."""
    db, q = data
    index = ivf_flat.build(db, ivf_flat.IndexParams(n_lists=32),
                           res=Resources(seed=5))
    sp = ivf_flat.SearchParams(n_probes=32, scan_dtype="bfloat16")
    _, i = ivf_flat.search(index, q, 10, sp)
    assert float(neighborhood_recall(np.asarray(i), gt)) >= 0.99
    with pytest.raises(ValueError, match="bfloat16"):
        ivf_flat.search(index, q, 10,
                        ivf_flat.SearchParams(n_probes=4, scan_dtype="float16"))
