"""The driver-visible bench line must carry hardware evidence even when
the TPU tunnel is down at capture time (VERDICT r4 weak #1).

``bench._last_measured_tpu`` scans committed ``BENCH_TPU_SESSION_r*.json``
artifacts for the newest driver-shaped on-chip row; ``main`` attaches it
as a labeled ``last_measured_tpu`` block whenever the run lands on CPU.
Reference analog: the benchmark JSON emission in
cpp/bench/ann/src/common/benchmark.hpp:379-509 (every run self-describes
its context in the emitted record)."""

import importlib.util
import json
import os

_HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_spec = importlib.util.spec_from_file_location(
    "bench_headline", os.path.join(_HERE, "bench.py"))
bench = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench)


def _write(dirpath, name, doc):
    with open(os.path.join(dirpath, name), "w") as f:
        json.dump(doc, f)


def test_none_when_no_artifacts(tmp_path):
    assert bench._last_measured_tpu(str(tmp_path)) is None


def test_ignores_cpu_rows(tmp_path):
    _write(tmp_path, "BENCH_TPU_SESSION_r03.json", {
        "when": "x", "bench_py_first_run": {
            "platform": "cpu", "value": 1.0}})
    assert bench._last_measured_tpu(str(tmp_path)) is None


def test_picks_newest_round_and_rerun_over_first(tmp_path):
    _write(tmp_path, "BENCH_TPU_SESSION_r03.json", {
        "when": "r3 window", "bench_py_first_run": {
            "platform": "tpu", "metric": "m", "value": 81420.1,
            "unit": "QPS", "recall": 1.0, "scan": "bf16+fp32refine"}})
    _write(tmp_path, "BENCH_TPU_SESSION_r04.json", {
        "when": "r4 window",
        "bench_py_first_run": {
            "platform": "tpu", "metric": "m", "value": 61349.6,
            "unit": "QPS", "recall": 1.0, "scan": "fp32",
            "extra": {"ivf_pq_nprobe32": {"qps": 97920.7}}},
        "bench_py_rerun": {
            "platform": "tpu", "metric": "m", "value": 70000.0,
            "unit": "QPS", "recall": 1.0, "scan": "fp32"}})
    block = bench._last_measured_tpu(str(tmp_path))
    assert block["value"] == 70000.0          # rerun beats first_run
    assert block["artifact"] == "BENCH_TPU_SESSION_r04.json"
    assert block["when"] == "r4 window"
    assert "on-chip" in block["note"]


def test_numeric_round_ordering(tmp_path):
    # r10 must beat r9 (numeric, not lexicographic, round comparison)
    _write(tmp_path, "BENCH_TPU_SESSION_r9.json", {
        "when": "r9", "bench_py_first_run": {
            "platform": "tpu", "metric": "m", "value": 9.0,
            "unit": "QPS", "recall": 1.0, "scan": "fp32"}})
    _write(tmp_path, "BENCH_TPU_SESSION_r10.json", {
        "when": "r10", "bench_py_first_run": {
            "platform": "tpu", "metric": "m", "value": 10.0,
            "unit": "QPS", "recall": 1.0, "scan": "fp32"}})
    assert bench._last_measured_tpu(str(tmp_path))["value"] == 10.0


def test_repo_artifact_resolves():
    # the real committed artifact must yield a block (the actual
    # round-close safety net, not just the synthetic fixtures)
    block = bench._last_measured_tpu(_HERE)
    assert block is not None
    assert block["value"] > 0
    assert block["artifact"].startswith("BENCH_TPU_SESSION_r")


def test_malformed_artifact_skipped(tmp_path):
    with open(os.path.join(tmp_path, "BENCH_TPU_SESSION_r09.json"),
              "w") as f:
        f.write("{not json")
    _write(tmp_path, "BENCH_TPU_SESSION_r04.json", {
        "when": "w", "bench_py_first_run": {
            "platform": "tpu", "metric": "m", "value": 5.0,
            "unit": "QPS", "recall": 1.0, "scan": "fp32"}})
    block = bench._last_measured_tpu(str(tmp_path))
    assert block["value"] == 5.0
