"""SelectAlgo.APPROX (TPU PartialReduce / lax.approx_min_k) semantics.

On CPU the approx primitive falls back to an exact implementation, so
these tests gate CONTRACT (shapes, ordering, recall floor, plumbing into
searches) — the speed claim is measured on hardware by
tools/select_k_bench.py / bench_ann.py."""

import numpy as np
import pytest

from raft_tpu.ops.select_k import SelectAlgo, select_k
from raft_tpu.stats import neighborhood_recall

pytestmark = pytest.mark.fast


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(3)
    return rng.standard_normal((64, 4096)).astype(np.float32)


def test_approx_recall_floor_and_order(data):
    k = 32
    v_e, i_e = select_k(data, k)
    v_a, i_a = select_k(data, k, algo=SelectAlgo.APPROX, recall_target=0.95)
    assert v_a.shape == (64, k) and i_a.shape == (64, k)
    # returned values ascend (sorted like DIRECT)
    va = np.asarray(v_a)
    assert (np.diff(va, axis=1) >= 0).all()
    rec = float(neighborhood_recall(np.asarray(i_a), np.asarray(i_e)))
    # 0.95 is the per-element EXPECTED recall on TPU hardware — assert
    # with slack so sampling variation doesn't flake the suite there
    assert rec >= 0.90


def test_approx_max_side(data):
    v_a, i_a = select_k(data, 8, select_min=False, algo=SelectAlgo.APPROX)
    v_e, _ = select_k(data, 8, select_min=False)
    # per-element ~95% guarantee, so on TPU a few rows may miss the true
    # max — require the bulk of rows to find it (CPU fallback: all)
    hit = np.mean(np.asarray(v_a)[:, 0] == np.asarray(v_e)[:, 0])
    assert hit >= 0.9


def test_search_select_recall_plumbs_through():
    from raft_tpu.neighbors import brute_force, ivf_flat, ivf_pq

    rng = np.random.default_rng(0)
    db = rng.standard_normal((4000, 32)).astype(np.float32)
    q = rng.standard_normal((100, 32)).astype(np.float32)
    _, gt = brute_force.knn(q, db, k=10, metric="sqeuclidean")
    gt = np.asarray(gt)

    _, i_bf = brute_force.search(
        brute_force.build(db, metric="sqeuclidean"), q, 10,
        select_recall=0.95)
    assert float(neighborhood_recall(np.asarray(i_bf), gt)) >= 0.9

    fl = ivf_flat.build(db, ivf_flat.IndexParams(n_lists=16))
    _, i_fl = ivf_flat.search(
        fl, q, 10, ivf_flat.SearchParams(n_probes=16, select_recall=0.95))
    assert float(neighborhood_recall(np.asarray(i_fl), gt)) >= 0.9

    pq = ivf_pq.build(db, ivf_pq.IndexParams(n_lists=16, pq_dim=16))
    _, i_pq = ivf_pq.search(
        pq, q, 10, ivf_pq.SearchParams(n_probes=16, select_recall=0.95))
    assert float(neighborhood_recall(np.asarray(i_pq), gt)) >= 0.8
