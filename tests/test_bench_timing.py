"""bench/timing.py — tunnel-safe fences and timed loops (CPU-checked).

On CPU the fence is redundant with block_until_ready, but every helper
must still return sane values and preserve results, since the same code
path produces all on-TPU artifacts."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.bench.timing import (chain_perturb, fence, prepare,
                                   time_dispatches, time_latency_chained)

pytestmark = pytest.mark.fast


def test_fence_handles_mixed_trees():
    x = jnp.arange(6.0).reshape(2, 3)
    fence({"a": x, "b": [x.astype(jnp.int32), None, "str"], "c": 3})
    fence(None)  # no leaves: no-op


def test_prepare_moves_to_device_and_roundtrips():
    h = np.random.default_rng(0).standard_normal((4, 8)).astype(np.float32)
    d = prepare({"x": h, "meta": "keep"})
    assert isinstance(d["x"], jax.Array)
    assert d["meta"] == "keep"
    np.testing.assert_array_equal(np.asarray(d["x"]), h)


def test_time_dispatches_positive_and_runs_fn():
    calls = []
    f = jax.jit(lambda x: (x * 2).sum())
    x = jnp.ones((64, 64))

    def dispatch():
        calls.append(1)
        return f(x)

    dt = time_dispatches(dispatch, iters=3, warmup=1)
    assert dt > 0
    assert len(calls) == 4  # warmup + iters


def test_chain_perturb_is_value_identity_but_dependent():
    x = jnp.arange(8.0)
    out = (jnp.ones((3,)), jnp.arange(3))
    y = chain_perturb(x, out)
    np.testing.assert_array_equal(np.asarray(y), np.asarray(x))
    y2 = chain_perturb(x, None)  # no leaves: passthrough
    np.testing.assert_array_equal(np.asarray(y2), np.asarray(x))


def test_time_latency_chained_serializes_and_returns_positive():
    f = jax.jit(lambda q: q @ q.T)
    q0 = jnp.ones((4, 4))

    def step(q):
        return chain_perturb(q0, f(q))

    dt = time_latency_chained(step, q0, iters=4)
    assert dt > 0


def test_time_latency_chained_rounds_collects_samples():
    from raft_tpu.bench.timing import last_info

    f = jax.jit(lambda q: q @ q.T)
    q0 = jnp.ones((4, 4))

    def step(q):
        return chain_perturb(q0, f(q))

    dt = time_latency_chained(step, q0, iters=4, rounds=5)
    samples = last_info["samples_s"]
    assert len(samples) == 5
    assert all(s > 0 for s in samples)
    # the return value is the mean of the recorded samples
    assert dt == pytest.approx(sum(samples) / len(samples))
    # a single-round call resets the samples to exactly one entry
    time_latency_chained(step, q0, iters=4)
    assert len(last_info["samples_s"]) == 1


def test_percentile_fields_shape():
    """The bench extras' latency percentile helper: nearest-rank keys the
    artifact schema promises (p50/p95/p99)."""
    from raft_tpu.serving.stats import percentiles

    pct = percentiles([0.001, 0.002, 0.040])  # one contended round
    assert set(pct) == {"p50", "p95", "p99"}
    assert pct["p99"] == 0.040  # the outlier survives; a mean hides it
