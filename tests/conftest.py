"""Test configuration: force an 8-device virtual CPU platform so sharding /
comms tests run anywhere (the driver separately dry-runs the multi-chip path
via __graft_entry__.dryrun_multichip). Must set flags before jax imports."""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
# Drop external PJRT plugin dirs (e.g. a TPU-tunnel plugin on PYTHONPATH):
# tests are CPU-only, and plugin registration can hang when the device
# tunnel behind it is unreachable.
sys.path[:] = [p for p in sys.path if ".axon_site" not in p]
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
import numpy as np  # noqa: E402
import pytest  # noqa: E402

# A TPU-tunnel plugin's sitecustomize may have set jax_platforms="axon,cpu"
# at interpreter startup (before this file ran), which overrides the env var
# above; backend init would then dial the tunnel and can hang forever.
# Force the config itself back to cpu-only for the test process.
jax.config.update("jax_platforms", "cpu")

# NOTE: do NOT enable the persistent compile cache here. On this image's
# XLA:CPU, cached AOT executables are compiled with machine features the
# loader reports as unsupported on the host ("+prefer-no-scatter … could
# lead to execution errors such as SIGILL"), and cache write/load paths
# have segfaulted mid-suite (ROUND_NOTES "Known flake"). The cache is the
# TPU-deployment feature (utils.enable_persistent_cache) — not a CPU CI
# accelerant.


@pytest.fixture(scope="session")
def devices():
    return jax.devices()


@pytest.fixture()
def rng():
    return np.random.default_rng(42)


def pytest_collection_modifyitems(config, items):
    """Everything not marked ``slow`` is the fast tier: ``pytest -m fast``
    gives a green signal in a few minutes, ``-m slow`` runs the heavy
    recall/scale suites (the reference's CI-vs-nightly split)."""
    for item in items:
        if "slow" not in item.keywords:
            item.add_marker(pytest.mark.fast)


@pytest.fixture()
def res():
    from raft_tpu import Resources

    return Resources(seed=42)
