"""Budget-capped IVF list padding + overflow block (VERDICT r2 #2).

The reference pays only group-of-32 padding on ragged lists
(neighbors/ivf_list.hpp); our dense [L, pad, ...] layout instead caps
``pad`` by a storage budget (list_packing.choose_list_pad) and spills hot
lists' tails into an overflow block that every query scans brute-force —
a strict candidate superset, so recall can only improve."""

import io

import numpy as np
import pytest

from raft_tpu import Resources
from raft_tpu.neighbors import brute_force, ivf_flat, ivf_pq, list_packing


def _skewed(rng, n, dim, hot_frac=0.5):
    """Clustered data with one hot blob: coarse k-means can't fully split
    it at small n_lists, so list sizes stay skewed."""
    n_hot = int(n * hot_frac)
    hot = rng.standard_normal((n_hot, dim)).astype(np.float32) * 0.05
    rest = rng.standard_normal((n - n_hot, dim)).astype(np.float32) * 0.05
    rest += rng.standard_normal((n - n_hot, 1)).astype(np.float32) * 3.0
    out = np.concatenate([hot, rest])
    rng.shuffle(out)
    return out


def test_choose_list_pad_honors_budget():
    rng = np.random.default_rng(0)
    for _ in range(20):
        n_lists = int(rng.integers(4, 300))
        # lognormal skew: a few hot lists, many small ones
        sizes = np.maximum(
            rng.lognormal(3.0, rng.uniform(0.1, 1.5), n_lists), 0
        ).astype(np.int64)
        n = int(sizes.sum())
        if n < n_lists * 8:  # below the align floor the bound relaxes
            continue
        pad = list_packing.choose_list_pad(sizes, max_expansion=1.5)
        overflow = int(np.maximum(sizes - pad, 0).sum())
        storage = n_lists * pad + (-(-overflow // 8) * 8 if overflow else 0)
        assert pad % 8 == 0
        assert storage <= 1.5 * n, (storage, n, pad)
        # balanced sizes must keep the max-driven pad (nothing spills)
        bal = np.full(n_lists, max(int(sizes.mean()), 8))
        pad_b = list_packing.choose_list_pad(bal, max_expansion=1.5)
        assert pad_b >= bal.max()


def test_sift1m_shape_padded_bytes_bound():
    """VERDICT r2 #2 'done' gate at the sift-1M/nlist=1024 shape: even a
    heavy-tailed size distribution (one list 50x the mean) stays within
    1.5x raw storage."""
    rng = np.random.default_rng(7)
    n, n_lists = 1_000_000, 1024
    sizes = rng.lognormal(0.0, 0.6, n_lists)
    sizes[0] *= 50.0  # pathological hot cluster
    sizes = (sizes / sizes.sum() * n).astype(np.int64)
    sizes[0] += n - sizes.sum()
    pad = list_packing.choose_list_pad(sizes, max_expansion=1.5)
    overflow = int(np.maximum(sizes - pad, 0).sum())
    padded_slots = n_lists * pad + (-(-overflow // 8) * 8 if overflow else 0)
    assert padded_slots <= 1.5 * n
    # ... while the max-driven layout would have blown far past it
    assert n_lists * (-(-int(sizes.max()) // 8) * 8) > 3 * n


def test_ivf_flat_overflow_superset_recall():
    """With a tight budget forcing spill, probing every list + overflow is
    a full exact scan: results must match brute force."""
    rng = np.random.default_rng(1)
    db = _skewed(rng, 3000, 24)
    q = _skewed(rng, 64, 24)
    params = ivf_flat.IndexParams(n_lists=16, list_pad_expansion=1.01)
    index = ivf_flat.build(db, params, res=Resources(seed=0))
    n_over = int((np.asarray(index.overflow_indices) >= 0).sum())
    assert n_over > 0, "expansion=1.01 on skewed data must spill"
    assert (int(np.asarray(index.list_sizes).sum()) + n_over) == len(db)
    d, i = ivf_flat.search(index, q, 10,
                           ivf_flat.SearchParams(n_probes=16))
    d_bf, i_bf = brute_force.knn(q, db, k=10, metric="sqeuclidean")
    np.testing.assert_allclose(np.asarray(d), np.asarray(d_bf), atol=1e-3)


def test_ivf_flat_overflow_filter_and_fast_scan():
    """Bitset filtering must apply to overflow rows too; the bf16 fast
    scan covers the overflow block as well."""
    from raft_tpu.core.bitset import Bitset

    rng = np.random.default_rng(2)
    db = _skewed(rng, 2000, 16)
    q = _skewed(rng, 32, 16)
    params = ivf_flat.IndexParams(n_lists=8, list_pad_expansion=1.01)
    index = ivf_flat.build(db, params, res=Resources(seed=0))
    over_ids = np.asarray(index.overflow_indices)
    over_ids = over_ids[over_ids >= 0]
    assert len(over_ids) > 0
    # filter OUT every overflow row: none may appear in results
    bs = Bitset.create(len(db), default=True)
    bs = bs.set(np.asarray(over_ids), False)
    _, i = ivf_flat.search(index, q, 10,
                           ivf_flat.SearchParams(n_probes=8), filter=bs)
    got = np.asarray(i)
    assert not np.isin(got[got >= 0], over_ids).any()
    # the bf16 fast scan must cover the overflow block too. Distances and
    # ranks are NOT comparable on this data (hot-blob rows are near-
    # equidistant and the rest have large norms → bf16 cancellation), so
    # assert participation: overflow rows show up in bf16 results roughly
    # as often as in fp32 results.
    _, i32 = ivf_flat.search(index, q, 10, ivf_flat.SearchParams(n_probes=8))
    _, i16 = ivf_flat.search(
        index, q, 10,
        ivf_flat.SearchParams(n_probes=8, scan_dtype="bfloat16"))
    hits32 = int(np.isin(np.asarray(i32), over_ids).sum())
    hits16 = int(np.isin(np.asarray(i16), over_ids).sum())
    assert hits32 > 0
    assert hits16 > hits32 // 2, (hits16, hits32)


def test_ivf_flat_extend_spills_and_serializes():
    rng = np.random.default_rng(3)
    db = _skewed(rng, 2400, 16)
    params = ivf_flat.IndexParams(n_lists=8, list_pad_expansion=1.01,
                                  add_data_on_build=False)
    base = ivf_flat.build(db, params, res=Resources(seed=0))
    index = ivf_flat.extend(base, db[:1200])
    index = ivf_flat.extend(index, db[1200:])
    n_over = int((np.asarray(index.overflow_indices) >= 0).sum())
    assert n_over > 0
    assert int(np.asarray(index.list_sizes).sum()) + n_over == len(db)
    # ids partition [0, n)
    ids = np.concatenate([
        np.asarray(index.list_indices).ravel(),
        np.asarray(index.overflow_indices)])
    ids = np.sort(ids[ids >= 0])
    np.testing.assert_array_equal(ids, np.arange(len(db)))
    # round-trip preserves the overflow block
    buf = io.BytesIO()
    ivf_flat.serialize(index, buf)
    buf.seek(0)
    back = ivf_flat.deserialize(buf)
    np.testing.assert_array_equal(np.asarray(back.overflow_data),
                                  np.asarray(index.overflow_data))
    np.testing.assert_array_equal(np.asarray(back.overflow_indices),
                                  np.asarray(index.overflow_indices))
    assert back.params.list_pad_expansion == params.list_pad_expansion
    d1, i1 = ivf_flat.search(index, db[:32], 5,
                             ivf_flat.SearchParams(n_probes=8))
    d2, i2 = ivf_flat.search(back, db[:32], 5,
                             ivf_flat.SearchParams(n_probes=8))
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))


def test_ivf_pq_overflow_both_engines():
    """Spilled PQ rows (decoded center+residual block) must be reachable
    through BOTH scan engines, with identical candidates at fp32 cache
    dtype (the engines share the exact ADC distance)."""
    rng = np.random.default_rng(4)
    db = _skewed(rng, 3000, 32)
    q = _skewed(rng, 48, 32)
    params = ivf_pq.IndexParams(n_lists=16, pq_dim=16,
                                list_pad_expansion=1.01)
    index = ivf_pq.build(db, params, res=Resources(seed=0))
    n_over = int((np.asarray(index.overflow_indices) >= 0).sum())
    assert n_over > 0
    sp_cache = ivf_pq.SearchParams(n_probes=16, scan_mode="cache",
                                   scan_cache_dtype=np.float32)
    sp_lut = ivf_pq.SearchParams(n_probes=16, scan_mode="lut",
                                 scan_cache_dtype=np.float32)
    d_c, i_c = ivf_pq.search(index, q, 10, sp_cache)
    d_l, i_l = ivf_pq.search(index, q, 10, sp_lut)
    np.testing.assert_allclose(np.asarray(d_c), np.asarray(d_l),
                               rtol=1e-4, atol=1e-3)
    # probing all lists + overflow covers every row: ADC recall vs exact
    # must match the uncapped index's (overflow costs no recall)
    full = ivf_pq.build(db, ivf_pq.IndexParams(
        n_lists=16, pq_dim=16, list_pad_expansion=1e9),
        res=Resources(seed=0))
    assert full.overflow_codes.shape[0] == 0
    d_f, i_f = ivf_pq.search(full, q, 10, sp_cache)
    from raft_tpu.stats import neighborhood_recall

    _, gt = brute_force.knn(q, db, k=10, metric="sqeuclidean")
    r_capped = neighborhood_recall(np.asarray(i_c), np.asarray(gt))
    r_full = neighborhood_recall(np.asarray(i_f), np.asarray(gt))
    assert r_capped >= r_full - 0.02, (r_capped, r_full)


def test_ivf_pq_extend_overflow_and_roundtrip():
    rng = np.random.default_rng(5)
    db = _skewed(rng, 2400, 32)
    params = ivf_pq.IndexParams(n_lists=8, pq_dim=16,
                                list_pad_expansion=1.01,
                                add_data_on_build=False)
    base = ivf_pq.build(db, params, res=Resources(seed=0))
    index = ivf_pq.extend(base, db[:1200])
    index = ivf_pq.extend(index, db[1200:])
    n_over = int((np.asarray(index.overflow_indices) >= 0).sum())
    assert n_over > 0
    ids = np.concatenate([
        np.asarray(index.list_indices).ravel(),
        np.asarray(index.overflow_indices)])
    ids = np.sort(ids[ids >= 0])
    np.testing.assert_array_equal(ids, np.arange(len(db)))
    buf = io.BytesIO()
    ivf_pq.serialize(index, buf)
    buf.seek(0)
    back = ivf_pq.deserialize(buf)
    np.testing.assert_array_equal(np.asarray(back.overflow_codes),
                                  np.asarray(index.overflow_codes))
    np.testing.assert_array_equal(np.asarray(back.overflow_labels),
                                  np.asarray(index.overflow_labels))
    d1, i1 = ivf_pq.search(index, db[:32], 5,
                           ivf_pq.SearchParams(n_probes=8))
    d2, i2 = ivf_pq.search(back, db[:32], 5,
                           ivf_pq.SearchParams(n_probes=8))
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))


def test_ooc_builds_spill_to_overflow(tmp_path):
    """Streamed from-file builds must apply the same budget cap + spill."""
    from raft_tpu import native
    from raft_tpu.neighbors import ooc

    rng = np.random.default_rng(6)
    db = _skewed(rng, 2000, 16)
    path = str(tmp_path / "skew.fbin")
    native.write_bin(path, db)
    fl = ooc.build_ivf_flat_from_file(
        path, ivf_flat.IndexParams(n_lists=8, list_pad_expansion=1.01),
        batch_rows=512)
    n_over = int((np.asarray(fl.overflow_indices) >= 0).sum())
    assert n_over > 0
    assert int(np.asarray(fl.list_sizes).sum()) + n_over == len(db)
    d, i = ivf_flat.search(fl, db[:16], 5, ivf_flat.SearchParams(n_probes=8))
    d_bf, _ = brute_force.knn(db[:16], db, k=5, metric="sqeuclidean")
    np.testing.assert_allclose(np.asarray(d), np.asarray(d_bf), atol=1e-3)

    pq = ooc.build_ivf_pq_from_file(
        path, ivf_pq.IndexParams(n_lists=8, pq_dim=16,
                                 list_pad_expansion=1.01),
        batch_rows=512)
    n_over_pq = int((np.asarray(pq.overflow_indices) >= 0).sum())
    assert n_over_pq > 0
    assert int(np.asarray(pq.list_sizes).sum()) + n_over_pq == len(db)
    ids = np.concatenate([np.asarray(pq.list_indices).ravel(),
                          np.asarray(pq.overflow_indices)])
    ids = np.sort(ids[ids >= 0])
    np.testing.assert_array_equal(ids, np.arange(len(db)))


@pytest.mark.slow
def test_sharded_builds_search_overflow():
    """Sharded builds must carry each shard's spill block into the SPMD
    search (code-review r3 finding: assemblers silently dropped it)."""
    from raft_tpu.parallel import comms as comms_mod
    from raft_tpu.parallel import sharded

    comms = comms_mod.init_comms(axis="overflow_test")
    rng = np.random.default_rng(11)
    db = _skewed(rng, 4096, 24)
    q = _skewed(rng, 40, 24)
    _, gt = brute_force.knn(q, db, k=10, metric="sqeuclidean")

    fl = sharded.build_ivf_flat(
        comms, db, ivf_flat.IndexParams(n_lists=8, list_pad_expansion=1.01))
    assert fl.overflow_data is not None, "skewed shards must spill"
    n_over = int((np.asarray(fl.overflow_indices) >= 0).sum())
    assert n_over > 0
    d, i = sharded.search_ivf_flat(fl, q, 10,
                                   ivf_flat.SearchParams(n_probes=8))
    # all lists + overflow probed → exact
    d_bf, _ = brute_force.knn(q, db, k=10, metric="sqeuclidean")
    np.testing.assert_allclose(np.asarray(d), np.asarray(d_bf), atol=1e-3)
    # overflow ids must be GLOBAL row ids (the in-memory builder offsets)
    over = np.asarray(fl.overflow_indices)
    assert over.max() >= 0 and over.max() < len(db)

    for mode in ("cache", "lut"):
        pq = sharded.build_ivf_pq(
            comms, db,
            ivf_pq.IndexParams(n_lists=8, pq_dim=8, kmeans_n_iters=4,
                               list_pad_expansion=1.01),
            scan_mode=mode)
        assert pq.overflow_decoded is not None
        d, i = sharded.search_ivf_pq(
            pq, q, 10, ivf_pq.SearchParams(n_probes=8, scan_mode=mode))
        from raft_tpu.stats import neighborhood_recall

        r = float(neighborhood_recall(np.asarray(i), np.asarray(gt)))
        # full probe: recall limited only by PQ quantization
        assert r >= 0.6, (mode, r)


def test_deserialize_v1_files_still_load():
    """Pre-overflow (v1) index files must keep loading (code-review r3:
    the v2 reader consumed v1 bytes unconditionally and derailed)."""
    from raft_tpu.core import serialize as ser

    rng = np.random.default_rng(8)
    db = rng.standard_normal((256, 16)).astype(np.float32)
    idx = ivf_flat.build(db, ivf_flat.IndexParams(n_lists=4),
                         res=Resources(seed=0))
    buf = io.BytesIO()
    w = ser.IndexWriter(buf, "ivf_flat", 1)  # v1 field set, no overflow
    w.scalar(int(idx.metric), "<i4")
    w.scalar(idx.params.n_lists, "<i8")
    w.scalar(idx.params.kmeans_n_iters, "<i4")
    w.scalar(idx.params.kmeans_trainset_fraction, "<f8")
    w.scalar(0, "<i4")
    w.scalar(idx.n_rows, "<i8")
    w.array(idx.centers)
    w.array(idx.list_data)
    w.array(idx.list_indices)
    w.array(idx.list_sizes)
    w.finish()
    buf.seek(0)
    back = ivf_flat.deserialize(buf)
    assert back.n_rows == idx.n_rows
    assert back.overflow_data.shape[0] == 0
    d1, i1 = ivf_flat.search(idx, db[:8], 3, ivf_flat.SearchParams(n_probes=4))
    d2, i2 = ivf_flat.search(back, db[:8], 3,
                             ivf_flat.SearchParams(n_probes=4))
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    pq = ivf_pq.build(db, ivf_pq.IndexParams(n_lists=4, pq_dim=8,
                                             kmeans_n_iters=4),
                      res=Resources(seed=0))
    buf = io.BytesIO()
    w = ser.IndexWriter(buf, "ivf_pq", 1)
    w.scalar(int(pq.metric), "<i4")
    w.scalar(pq.params.n_lists, "<i8")
    w.scalar(pq.params.kmeans_n_iters, "<i4")
    w.scalar(pq.params.kmeans_trainset_fraction, "<f8")
    w.scalar(pq.params.pq_bits, "<i4")
    w.scalar(pq.pq_dim, "<i4")
    w.scalar(int(pq.params.codebook_kind), "<i4")
    w.scalar(0, "<i4")
    w.scalar(pq.n_rows, "<i8")
    w.array(pq.centers)
    w.array(pq.rotation)
    w.array(pq.codebooks)
    w.array(pq.list_codes)
    w.array(pq.list_indices)
    w.array(pq.list_sizes)
    w.finish()
    buf.seek(0)
    back = ivf_pq.deserialize(buf)
    assert back.n_rows == pq.n_rows
    assert back.overflow_codes.shape[0] == 0
