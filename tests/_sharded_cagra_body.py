"""Body of test_sharded_cagra, executed in a fresh subprocess (see the
test's docstring: a fresh process sidesteps an environment-level XLA:CPU
compile segfault that only appears deep into a long-lived test process).
Not collected by pytest (module name starts with an underscore)."""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
sys.path[:] = [p for p in sys.path if ".axon_site" not in p]

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

from raft_tpu.neighbors import brute_force, cagra
from raft_tpu.parallel import comms as comms_mod, sharded
from raft_tpu.stats import neighborhood_recall


def main():
    comms = comms_mod.init_comms(axis="data")
    assert comms.size == 8
    rng = np.random.default_rng(5)
    # clustered so the graph walk converges quickly
    centers = rng.standard_normal((20, 16)) * 6.0
    db = (centers[rng.integers(0, 20, 2000)]
          + rng.standard_normal((2000, 16))).astype(np.float32)
    q = db[:40] + 0.01 * rng.standard_normal((40, 16)).astype(np.float32)
    _, gt = brute_force.knn(q, db, k=5, metric="sqeuclidean")
    idx = sharded.build_cagra(
        comms, db, cagra.IndexParams(graph_degree=16,
                                     intermediate_graph_degree=32))
    d, i = sharded.search_cagra(idx, q, 5, cagra.SearchParams(itopk_size=32))
    i = np.asarray(i)
    assert i.shape == (40, 5)
    assert (i < 2000).all() and (i >= -1).all()
    recall = float(neighborhood_recall(i, np.asarray(gt)))
    assert recall >= 0.8, f"sharded cagra recall {recall}"
    # merge ladder: every cross-chip merge schedule is bit-identical to
    # the all_gather reference (docs/sharding.md)
    sp = cagra.SearchParams(itopk_size=32)
    d_ref, i_ref = sharded.search_cagra(idx, q, 5, sp,
                                        merge_mode="allgather")
    for mode in ("tree", "ring"):
        dm, im = sharded.search_cagra(idx, q, 5, sp, merge_mode=mode)
        np.testing.assert_array_equal(np.asarray(dm), np.asarray(d_ref),
                                      err_msg=f"cagra {mode} dist")
        np.testing.assert_array_equal(np.asarray(im), np.asarray(i_ref),
                                      err_msg=f"cagra {mode} ids")
    print("SHARDED_CAGRA_OK", recall)


if __name__ == "__main__":
    main()
