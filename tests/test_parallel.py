"""Distributed-layer tests on the 8-device virtual CPU mesh — the simulated
backend seam the reference lacks (SURVEY.md §4: raft-dask test_comms.py runs
collectives on a LocalCUDACluster; here the mesh is the cluster)."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from raft_tpu.parallel import comms as comms_mod
from raft_tpu.parallel import sharded
from raft_tpu.neighbors import brute_force
from raft_tpu.stats import neighborhood_recall


@pytest.fixture(scope="module")
def comms():
    assert len(jax.devices()) == 8, "conftest should provide 8 CPU devices"
    return comms_mod.init_comms(axis="data")


def test_comms_size_and_selftests(comms):
    assert comms.size == 8
    assert comms_mod.test_collective_allreduce(comms)
    assert comms_mod.test_collective_allgather(comms)
    assert comms_mod.test_collective_reducescatter(comms)
    assert comms_mod.test_pointToPoint_simple_send_recv(comms)


def test_comm_split():
    devs = jax.devices()
    c = comms_mod.init_comms(devs, axis="rows", mesh_shape=(4, 2),
                             axis_names=("rows", "cols"))
    assert c.size == 4
    c2 = c.comm_split("cols")
    assert c2.size == 2
    with pytest.raises(ValueError, match="not in mesh"):
        c.comm_split("nope")


def test_reduce_ops(comms):
    import jax.numpy as jnp

    x = comms.shard(jnp.arange(8, dtype=jnp.float32)[:, None], P("data"))

    def body(xs):
        v = xs[0, 0]
        return (comms.allreduce(v, "sum"), comms.allreduce(v, "max"),
                comms.allreduce(v, "min"))

    s, mx, mn = jax.jit(comms.run(body, P("data"), (P(), P(), P())))(x)
    assert float(s) == sum(range(8))
    assert float(mx) == 7.0
    assert float(mn) == 0.0


def test_sharded_knn_matches_single_device(comms):
    rng = np.random.default_rng(0)
    db = rng.standard_normal((1000, 16)).astype(np.float32)
    q = rng.standard_normal((32, 16)).astype(np.float32)
    d_ref, i_ref = brute_force.knn(q, db, k=10, metric="sqeuclidean")
    d, i = sharded.knn(comms, q, db, k=10, metric="sqeuclidean")
    assert float(neighborhood_recall(np.asarray(i), np.asarray(i_ref))) >= 0.999
    np.testing.assert_allclose(np.asarray(d), np.asarray(d_ref), rtol=1e-3,
                               atol=1e-3)


def test_sharded_knn_unpadded_rows(comms):
    # n not divisible by 8 exercises the padding mask
    rng = np.random.default_rng(1)
    db = rng.standard_normal((1003, 16)).astype(np.float32)
    q = rng.standard_normal((16, 16)).astype(np.float32)
    d_ref, i_ref = brute_force.knn(q, db, k=5, metric="sqeuclidean")
    d, i = sharded.knn(comms, q, db, k=5)
    assert float(neighborhood_recall(np.asarray(i), np.asarray(i_ref))) >= 0.999


def test_sharded_kmeans(comms):
    rng = np.random.default_rng(2)
    centers = rng.standard_normal((8, 16)) * 10
    labels = rng.integers(0, 8, 2000)
    x = (centers[labels] + rng.standard_normal((2000, 16))).astype(np.float32)
    c, got = sharded.kmeans_fit(comms, x, 8, n_iters=15,
                                key=jax.random.key(12))
    assert c.shape == (8, 16)
    got = np.asarray(got)
    # cluster purity: every true cluster maps to one dominant found label
    purity = 0
    for t in range(8):
        members = got[labels == t]
        purity += np.bincount(members, minlength=8).max()
    # plain Lloyd with random init occasionally merges two blobs; the gate
    # checks the distributed EM works, not init quality
    assert purity / len(x) >= 0.9


@pytest.mark.slow
def test_sharded_ivf_flat(comms):
    from raft_tpu.neighbors import ivf_flat

    rng = np.random.default_rng(3)
    db = rng.standard_normal((4000, 24)).astype(np.float32)
    q = rng.standard_normal((50, 24)).astype(np.float32)
    _, gt = brute_force.knn(q, db, k=10, metric="sqeuclidean")
    idx = sharded.build_ivf_flat(comms, db, ivf_flat.IndexParams(n_lists=8))
    d, i = sharded.search_ivf_flat(idx, q, 10,
                                   ivf_flat.SearchParams(n_probes=8))
    recall = float(neighborhood_recall(np.asarray(i), np.asarray(gt)))
    assert recall >= 0.999, f"sharded ivf_flat recall {recall}"
    # sharded search honors the bf16 fast scan too
    d, i = sharded.search_ivf_flat(
        idx, q, 10, ivf_flat.SearchParams(n_probes=8, scan_dtype="bfloat16"))
    recall = float(neighborhood_recall(np.asarray(i), np.asarray(gt)))
    assert recall >= 0.99, f"sharded bf16 ivf_flat recall {recall}"


@pytest.mark.slow
def test_sharded_ivf_pq(comms):
    from raft_tpu.neighbors import ivf_pq

    rng = np.random.default_rng(4)
    db = rng.standard_normal((4000, 32)).astype(np.float32)
    q = rng.standard_normal((50, 32)).astype(np.float32)
    _, gt = brute_force.knn(q, db, k=10, metric="sqeuclidean")
    idx = sharded.build_ivf_pq(
        comms, db, ivf_pq.IndexParams(n_lists=8, pq_dim=16, pq_bits=8,
                                      kmeans_n_iters=5))
    d, i = sharded.search_ivf_pq(idx, q, 10, ivf_pq.SearchParams(n_probes=8))
    i = np.asarray(i)
    assert i.shape == (50, 10)
    recall = float(neighborhood_recall(i, np.asarray(gt)))
    # full-probe PQ scan: recall limited only by quantization
    assert recall >= 0.7, f"sharded ivf_pq recall {recall}"


@pytest.mark.slow
def test_sharded_ivf_pq_lut_matches_cache(comms):
    """The memory-lean LUT engine under sharding must agree with the decoded
    cache engine (VERDICT r1 #7 gate). fp32 cache dtype → bit-exact ADC on
    both paths → identical neighbor sets."""
    from raft_tpu.neighbors import ivf_pq

    rng = np.random.default_rng(6)
    db = rng.standard_normal((2400, 32)).astype(np.float32)
    q = rng.standard_normal((40, 32)).astype(np.float32)
    from raft_tpu import Resources

    params = ivf_pq.IndexParams(n_lists=8, pq_dim=16, pq_bits=8,
                                kmeans_n_iters=4)
    # identical seeds → identical per-shard indexes; fp32 cache → both
    # engines evaluate the exact same ADC quantity
    cache_idx = sharded.build_ivf_pq(comms, db, params, res=Resources(seed=9),
                                     scan_mode="cache",
                                     scan_cache_dtype=jnp.float32)
    # scan_cache_dtype also governs the overflow-block decode for lut
    # builds: leaving it bf16 here would let spilled rows' distances drift
    # past rtol while the probed-list scans agree bit-for-bit
    lut_idx = sharded.build_ivf_pq(comms, db, params, res=Resources(seed=9),
                                   scan_mode="lut",
                                   scan_cache_dtype=jnp.float32)
    assert lut_idx.list_decoded is None  # memory-lean: no decoded cache
    assert lut_idx.list_codes is not None

    d_c, i_c = sharded.search_ivf_pq(cache_idx, q, 10,
                                     ivf_pq.SearchParams(n_probes=8))
    d_l, i_l = sharded.search_ivf_pq(
        lut_idx, q, 10, ivf_pq.SearchParams(n_probes=8, scan_mode="lut"))
    # same build seeds → same per-shard indexes; engines must agree
    np.testing.assert_allclose(np.asarray(d_l), np.asarray(d_c),
                               rtol=1e-4, atol=1e-4)
    overlap = np.mean([
        len(set(a) & set(b)) / 10.0
        for a, b in zip(np.asarray(i_l), np.asarray(i_c))])
    assert overlap >= 0.95, f"lut/cache neighbor overlap {overlap}"
    # engine-mismatch guards
    with pytest.raises(ValueError, match="no decoded cache"):
        sharded.search_ivf_pq(lut_idx, q, 10,
                              ivf_pq.SearchParams(scan_mode="cache"))
    with pytest.raises(ValueError, match="no packed codes"):
        sharded.search_ivf_pq(cache_idx, q, 10,
                              ivf_pq.SearchParams(scan_mode="lut"))


def test_ring_pairwise_distance_matches_single_device(comms):
    """Ring-scheduled MNMG pairwise (x stationary, y rotating via
    ppermute) must equal the single-device engine bit-for-bit."""
    from raft_tpu.ops.distance import pairwise_distance as pd_single

    rng = np.random.default_rng(12)
    x = rng.standard_normal((130, 24)).astype(np.float32)
    y = rng.standard_normal((75, 24)).astype(np.float32)
    for metric in ("sqeuclidean", "cosine", "inner_product"):
        got = np.asarray(sharded.pairwise_distance(comms, x, y, metric))
        want = np.asarray(pd_single(x, y, metric))
        assert got.shape == want.shape == (130, 75)
        np.testing.assert_allclose(got, want, atol=1e-4, err_msg=metric)


def test_allgatherv_gatherv(comms):
    counts = [(r % 3) + 1 for r in range(comms.size)]
    cap = max(counts)
    x = np.zeros((comms.size, cap, 2), np.float32)
    for r in range(comms.size):
        x[r, :counts[r]] = r + 1
    xs = comms.shard(jnp.asarray(x), P(comms.axis))

    def body(v):
        return comms.allgatherv(v[0], counts)

    out = np.asarray(jax.jit(comms.run(body, P(comms.axis), P()))(xs))
    want = np.concatenate([np.full((counts[r], 2), r + 1, np.float32)
                           for r in range(comms.size)])
    np.testing.assert_allclose(out, want)


def test_device_send_recv_and_multicast(comms):
    n = comms.size
    x = jnp.arange(n, dtype=jnp.float32)[:, None]
    xs = comms.shard(x, P(comms.axis))

    # reversal permutation
    table = list(reversed(range(n)))

    def body(v):
        return comms.device_send_recv(v, table)

    out = np.asarray(jax.jit(comms.run(body, P(comms.axis),
                                       P(comms.axis)))(xs))
    want = np.zeros(n)
    for r, d in enumerate(table):
        want[d] = r
    np.testing.assert_allclose(out.ravel(), want)

    # multicast root 0 → ranks {1, 2}
    def body2(v):
        return comms.device_multicast_sendrecv(v[0], 0, [1, 2])

    out2 = np.asarray(jax.jit(comms.run(body2, P(comms.axis),
                                        P(comms.axis)))(xs))
    want2 = np.arange(n, dtype=np.float32)
    want2[1] = 0
    want2[2] = 0
    np.testing.assert_allclose(out2.ravel(), want2)


@pytest.mark.slow
def test_sharded_cagra(tmp_path):
    """Runs in a fresh subprocess: compiling the nn_descent build program
    ~300 tests into a long-lived process intermittently segfaults this
    image's XLA:CPU (LLVM JIT; see ROUND_NOTES "Known flake") — the same
    compile is reliable in a fresh process, which is also how real
    deployments encounter it."""
    import pathlib
    import subprocess
    import sys

    body = pathlib.Path(__file__).with_name("_sharded_cagra_body.py")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    env["PYTHONPATH"] = str(pathlib.Path(__file__).resolve().parents[1])
    r = subprocess.run([sys.executable, str(body)], capture_output=True,
                       text=True, timeout=900, env=env)
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-3000:]
    assert "SHARDED_CAGRA_OK" in r.stdout, r.stdout[-3000:]


# ------------------------------------------- per-shard search trace spans


class TestShardedSearchSpans:
    """``set_span_sink()`` flips every search entrypoint onto the two-phase
    dispatch (local scan sharded, per-shard fence, host-side
    ``_elastic_merge``) — results must stay bit-identical to the fused
    single-program path, and the tape must carry one ``shard_search``
    child per rank under the parent's trace id."""

    def _run_instrumented(self, fn):
        from raft_tpu.obs import spans as obs_spans

        sink = obs_spans.ListSink()
        prev = sharded.set_span_sink(sink)
        try:
            out = fn()
        finally:
            sharded.set_span_sink(prev)
        return out, sink.records

    def _check_spans(self, records, family, size=8):
        children = [r for r in records if r["kind"] == "shard_search"]
        parents = [r for r in records if r["kind"] == "sharded_search"]
        assert len(parents) == 1
        parent = parents[0]
        assert parent["family"] == family
        assert parent["n_shards"] == size
        assert sorted(c["rank"] for c in children) == list(range(size))
        assert all(c["trace_id"] == parent["trace_id"] for c in children)
        assert all(c["family"] == family for c in children)
        # one distinct device per shard; timing fields present
        assert len({c["device"] for c in children}) == size
        for key in ("launch_ms", "merge_ms", "total_ms"):
            assert parent[key] >= 0.0
        assert all(c["device_ms"] >= 0.0 for c in children)

    def test_set_span_sink_returns_previous(self):
        marker = object()
        assert sharded.set_span_sink(marker) is None
        assert sharded.set_span_sink(None) is marker
        assert sharded._span_sink() is None

    def test_knn_spans_and_parity(self, comms, rng):
        data = rng.standard_normal((1000, 32)).astype(np.float32)
        q = rng.standard_normal((20, 32)).astype(np.float32)
        v0, i0 = sharded.knn(comms, q, data, k=10)
        (v1, i1), records = self._run_instrumented(
            lambda: sharded.knn(comms, q, data, k=10))
        np.testing.assert_array_equal(np.asarray(v0), np.asarray(v1))
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        self._check_spans(records, "brute_force")

    @pytest.mark.slow
    def test_ivf_flat_spans_and_parity(self, comms, rng):
        from raft_tpu.neighbors import ivf_flat

        data = rng.standard_normal((800, 32)).astype(np.float32)
        q = rng.standard_normal((16, 32)).astype(np.float32)
        idx = sharded.build_ivf_flat(comms, data,
                                     ivf_flat.IndexParams(n_lists=8))
        params = ivf_flat.SearchParams(n_probes=4)
        v0, i0 = sharded.search_ivf_flat(idx, q, 10, params)
        (v1, i1), records = self._run_instrumented(
            lambda: sharded.search_ivf_flat(idx, q, 10, params))
        np.testing.assert_array_equal(np.asarray(v0), np.asarray(v1))
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        self._check_spans(records, "ivf_flat")

    @pytest.mark.slow
    def test_ivf_pq_spans_and_parity(self, comms, rng):
        from raft_tpu.neighbors import ivf_pq

        data = rng.standard_normal((800, 32)).astype(np.float32)
        q = rng.standard_normal((16, 32)).astype(np.float32)
        idx = sharded.build_ivf_pq(comms, data,
                                   ivf_pq.IndexParams(n_lists=8, pq_dim=8))
        params = ivf_pq.SearchParams(n_probes=4)
        v0, i0 = sharded.search_ivf_pq(idx, q, 8, params)
        (v1, i1), records = self._run_instrumented(
            lambda: sharded.search_ivf_pq(idx, q, 8, params))
        np.testing.assert_array_equal(np.asarray(v0), np.asarray(v1))
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        self._check_spans(records, "ivf_pq")

    def test_no_sink_emits_nothing(self, comms, rng):
        """Default path: no sink, no spans — the zero-overhead guarantee."""
        from raft_tpu.obs import spans as obs_spans

        data = rng.standard_normal((256, 16)).astype(np.float32)
        q = rng.standard_normal((4, 16)).astype(np.float32)
        sink = obs_spans.ListSink()
        # sink NOT installed
        sharded.knn(comms, q, data, k=4)
        assert sink.records == []
