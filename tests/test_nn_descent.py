"""NN-descent tests — graph recall against exact kNN ground truth
(reference pattern: cpp/test/neighbors/ann_nn_descent.cuh)."""

import numpy as np
import pytest

from raft_tpu.neighbors import brute_force, nn_descent
from raft_tpu.stats import neighborhood_recall


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(5)
    return rng.standard_normal((2000, 32)).astype(np.float32)


@pytest.mark.slow
def test_graph_recall(data):
    params = nn_descent.IndexParams(
        graph_degree=32, intermediate_graph_degree=48, max_iterations=12)
    index = nn_descent.build(data, params)
    assert index.graph.shape == (len(data), 32)
    _, gt = brute_force.knn(data, data, k=33, metric="sqeuclidean")
    gt = np.asarray(gt)[:, 1:33]  # drop self
    got = np.asarray(index.graph)[:, :32]
    recall = float(neighborhood_recall(got, gt))
    assert recall >= 0.9, f"graph recall {recall}"


@pytest.mark.slow
def test_no_self_loops(data):
    params = nn_descent.IndexParams(
        graph_degree=16, intermediate_graph_degree=32, max_iterations=8)
    index = nn_descent.build(data, params)
    g = np.asarray(index.graph)
    assert not (g == np.arange(len(data))[:, None]).any()


@pytest.mark.slow
def test_graph_recall_50k_clustered():
    """GNND-fidelity gate at scale (VERDICT r1 #6): ≥0.9 recall at 50k×96 on
    clustered data within the iteration budget — the regime where a
    forward-only join stalls (clusters trap edge propagation without the
    symmetric reverse join)."""
    rng = np.random.default_rng(17)
    centers = rng.standard_normal((200, 96)).astype(np.float32) * 3.0
    labels = rng.integers(0, 200, 50_000)
    db = (centers[labels]
          + rng.standard_normal((50_000, 96))).astype(np.float32)

    params = nn_descent.IndexParams(
        graph_degree=32, intermediate_graph_degree=64, max_iterations=20)
    index = nn_descent.build(db, params)
    assert index.graph.shape == (50_000, 32)

    # exact ground truth on a node subsample (full 50k×50k is CI-hostile)
    sample = rng.choice(50_000, 800, replace=False)
    _, gt = brute_force.knn(db[sample], db, k=33, metric="sqeuclidean")
    gt = np.asarray(gt)
    # drop self wherever it appears (clustered data can have ties)
    gt_rows = []
    for r, row in enumerate(gt):
        row = row[row != sample[r]][:32]
        gt_rows.append(row)
    gt = np.stack(gt_rows)
    got = np.asarray(index.graph)[sample]
    recall = float(neighborhood_recall(got, gt))
    assert recall >= 0.9, f"50k clustered graph recall {recall}"


@pytest.mark.slow
def test_cagra_graph_quality_nn_descent_vs_ivf_pq():
    """CAGRA's two knn-graph build paths must deliver comparable search
    recall (reference: cagra_build.cuh IVF_PQ vs NN_DESCENT build_algo) —
    the gate that nn_descent is good enough to feed the flagship index."""
    from raft_tpu.neighbors import cagra
    from raft_tpu.stats import neighborhood_recall as nr

    rng = np.random.default_rng(23)
    db = rng.standard_normal((6000, 48)).astype(np.float32)
    q = rng.standard_normal((100, 48)).astype(np.float32)
    _, gt = brute_force.knn(q, db, k=10, metric="sqeuclidean")
    gt = np.asarray(gt)

    recalls = {}
    for algo in (cagra.BuildAlgo.NN_DESCENT, cagra.BuildAlgo.IVF_PQ):
        idx = cagra.build(db, cagra.IndexParams(
            intermediate_graph_degree=48, graph_degree=24, build_algo=algo))
        _, i = cagra.search(idx, q, 10,
                            cagra.SearchParams(itopk_size=64, search_width=2))
        recalls[algo.name] = float(nr(np.asarray(i), gt))
    assert recalls["NN_DESCENT"] >= 0.9, recalls
    # nn_descent graphs must not trail the ivf_pq path materially
    assert recalls["NN_DESCENT"] >= recalls["IVF_PQ"] - 0.05, recalls


def test_metric_validation():
    with pytest.raises(ValueError, match="supports"):
        nn_descent.IndexParams(metric="canberra")
