"""NN-descent tests — graph recall against exact kNN ground truth
(reference pattern: cpp/test/neighbors/ann_nn_descent.cuh)."""

import numpy as np
import pytest

from raft_tpu.neighbors import brute_force, nn_descent
from raft_tpu.stats import neighborhood_recall


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(5)
    return rng.standard_normal((2000, 32)).astype(np.float32)


@pytest.mark.slow
def test_graph_recall(data):
    params = nn_descent.IndexParams(
        graph_degree=32, intermediate_graph_degree=48, max_iterations=12)
    index = nn_descent.build(data, params)
    assert index.graph.shape == (len(data), 32)
    _, gt = brute_force.knn(data, data, k=33, metric="sqeuclidean")
    gt = np.asarray(gt)[:, 1:33]  # drop self
    got = np.asarray(index.graph)[:, :32]
    recall = float(neighborhood_recall(got, gt))
    assert recall >= 0.9, f"graph recall {recall}"


@pytest.mark.slow
def test_no_self_loops(data):
    params = nn_descent.IndexParams(
        graph_degree=16, intermediate_graph_degree=32, max_iterations=8)
    index = nn_descent.build(data, params)
    g = np.asarray(index.graph)
    assert not (g == np.arange(len(data))[:, None]).any()


def test_metric_validation():
    with pytest.raises(ValueError, match="supports"):
        nn_descent.IndexParams(metric="canberra")
