"""Scalar quantization (legacy ann_quantized role): int8 codes keep
brute-force recall high and round-trip within one grid step."""

import numpy as np

from raft_tpu.neighbors import brute_force, quantize
from raft_tpu.stats import neighborhood_recall


def test_roundtrip_within_grid_step():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((2000, 32)).astype(np.float32)
    sq = quantize.ScalarQuantizer.fit(x)
    codes = sq.transform(x)
    assert codes.dtype == np.int8
    rec = sq.inverse_transform(codes)
    np.testing.assert_allclose(rec, x, atol=np.max(sq.scale) * 0.51)


def test_quantized_knn_recall():
    # clustered data (iid gaussian has near-tie neighbor gaps that 8-bit
    # noise flips — unrepresentative of the benchmark datasets)
    rng = np.random.default_rng(1)
    centers = rng.standard_normal((40, 64)) * 4.0
    db = (centers[rng.integers(0, 40, 4000)]
          + rng.standard_normal((4000, 64))).astype(np.float32)
    q = (centers[rng.integers(0, 40, 100)]
         + rng.standard_normal((100, 64))).astype(np.float32)
    _, gt = brute_force.knn(q, db, k=10, metric="sqeuclidean")
    sq = quantize.ScalarQuantizer.fit(db, quantile=0.995)
    dbq, qq = sq.transform(db), sq.transform(q)
    d, i = brute_force.knn(qq, dbq, 10, metric="sqeuclidean")
    # contract 1: the int8 search path is EXACT on the codes
    ref = ((qq.astype(np.float32)[:, None]
            - dbq.astype(np.float32)[None]) ** 2).sum(-1)
    i_ref = np.argsort(ref, 1)[:, :10]
    assert float(neighborhood_recall(np.asarray(i), i_ref)) == 1.0
    # contract 2: 8-bit noise costs bounded recall vs fp32 ground truth
    rec = float(neighborhood_recall(np.asarray(i), np.asarray(gt)))
    assert rec >= 0.75, f"int8 recall {rec}"


def test_outlier_trim_saturates():
    rng = np.random.default_rng(2)
    x = rng.standard_normal((1000, 8)).astype(np.float32)
    x[0, 0] = 1e6  # single outlier must not stretch the grid
    sq = quantize.ScalarQuantizer.fit(x, quantile=0.99)
    codes = sq.transform(x)
    assert codes[0, 0] == 127  # saturated
    # grid still resolves the non-saturated bulk
    rec = sq.inverse_transform(codes[1:])
    inside = (codes[1:] > -128) & (codes[1:] < 127)
    err = np.abs(rec - x[1:])
    assert err[inside].max() <= np.max(sq.scale) * 0.6
