"""Execution-plan attribution (docs/observability.md "Query explain").

The contract under test: every family ``search()`` resolves to exactly
one reason-coded :class:`~raft_tpu.obs.explain.ExplainRecord`, the
record never perturbs the answer (bit-identity against the plain call),
the ``raft_tpu_dispatch_total`` counter reconciles with what actually
ran (zero ``unknown``-reason increments, ever), and the TPU no-verdict
warning fires exactly once per process."""

import logging

import jax
import numpy as np
import pytest

from raft_tpu.neighbors import brute_force, cagra, ivf_flat, ivf_pq
from raft_tpu.obs import explain as obs_explain
from raft_tpu.obs import metrics as obm
from raft_tpu.ops import pallas_kernels as pk
from raft_tpu.ops.select_k import select_k_plan

pytestmark = pytest.mark.fast

DIM = 24
K = 5
N = 600


@pytest.fixture(scope="module")
def db():
    rng = np.random.default_rng(11)
    return rng.standard_normal((N, DIM)).astype(np.float32)


@pytest.fixture(scope="module")
def queries():
    rng = np.random.default_rng(12)
    return rng.standard_normal((4, DIM)).astype(np.float32)


# ------------------------------------------------------- record plumbing

def test_record_dispatch_rejects_unvocabularied_reason():
    with pytest.raises(ValueError, match="reason"):
        obs_explain.record_dispatch("brute_force", "auto", "xla",
                                    "because_i_said_so")


def test_capture_stack_nests_and_isolates():
    with obs_explain.capture() as outer:
        obs_explain.record_dispatch("brute_force", "auto", "xla", "forced")
        with obs_explain.capture() as inner:
            obs_explain.record_dispatch("ivf_flat", "auto", "xla",
                                        "forced")
        # nested scope sees only its own record; outer sees both
        assert [r.family for r in inner.records] == ["ivf_flat"]
        assert [r.family for r in outer.records] == ["brute_force",
                                                     "ivf_flat"]
        assert outer.last.family == "ivf_flat"
    # no open capture: recording still counts, just lands nowhere
    rec = obs_explain.record_dispatch("cagra", "auto", "xla",
                                      "only_engine")
    assert rec.brief()["reason"] == "only_engine"


def test_record_serializes_and_briefs():
    rec = obs_explain.record_dispatch(
        "ivf_pq", "auto", "cache", "tpu_absent",
        params={"k": 10}, plan={"q_tile": 64})
    d = rec.to_dict()
    assert d["family"] == "ivf_pq" and d["plan"]["q_tile"] == 64
    assert set(rec.brief()) == {"family", "requested", "engine", "reason"}


# --------------------------------------- family parity + counter hygiene

def _build_family(family, db):
    if family == "brute_force":
        return brute_force.build(db)
    if family == "ivf_flat":
        return ivf_flat.build(db, ivf_flat.IndexParams(n_lists=8))
    if family == "ivf_pq":
        return ivf_pq.build(db, ivf_pq.IndexParams(n_lists=8, pq_dim=8))
    return cagra.build(db, cagra.IndexParams(graph_degree=8))


def _search_family(family, idx, queries, explain):
    if family == "brute_force":
        return brute_force.search(idx, queries, K, explain=explain)
    if family == "ivf_flat":
        return ivf_flat.search(idx, queries, K,
                               ivf_flat.SearchParams(n_probes=4),
                               explain=explain)
    if family == "ivf_pq":
        return ivf_pq.search(idx, queries, K,
                             ivf_pq.SearchParams(n_probes=4),
                             explain=explain)
    return cagra.search(idx, queries, K, explain=explain)


@pytest.mark.parametrize("family", ["brute_force", "ivf_flat", "ivf_pq",
                                    "cagra"])
def test_explain_bit_identical_and_reason_coded(family, db, queries):
    idx = _build_family(family, db)
    before = obs_explain.dispatch_counts()
    v0, i0 = _search_family(family, idx, queries, explain=False)
    v1, i1, rec = _search_family(family, idx, queries, explain=True)
    # the attribution is an observer: the answer is bit-identical
    assert np.array_equal(np.asarray(v0), np.asarray(v1))
    assert np.array_equal(np.asarray(i0), np.asarray(i1))
    assert rec.family == family
    assert rec.reason in obs_explain.REASONS
    assert rec.reason != "unknown"
    assert rec.params["k"] == K and rec.params["nq"] == 4
    # every dispatch lands on the counter — two searches, two counts
    after = obs_explain.dispatch_counts()
    key = (family, rec.engine, rec.reason)
    assert after[key] - before.get(key, 0) == 2
    # zero unknown-reason increments, ever (the schema escape hatch is
    # for readers of foreign artifacts, never for this codebase to emit)
    assert not any(k[2] == "unknown" for k in after)


def test_explain_returns_plan_tiles_on_xla_paths(db, queries):
    _, _, rec = _search_family("ivf_flat", _build_family("ivf_flat", db),
                               queries, explain=True)
    if rec.engine == "xla":  # the CPU-CI resolution
        assert rec.reason == "tpu_absent"
        assert rec.plan["predicted_workspace_bytes"] > 0
        assert rec.plan["q_tile"] >= 1
    # select_k resolution rides as notes at TRACE time only — force a
    # retrace so the note lands regardless of jit-cache state
    jax.clear_caches()
    _, _, rec = _search_family("ivf_flat", _build_family("ivf_flat", db),
                               queries, explain=True)
    assert any(n.get("op") == "select_k" for n in rec.notes)


def test_select_k_plan_matches_note(db, queries):
    jax.clear_caches()  # notes are captured at trace time (see above)
    _, _, rec = _search_family("brute_force",
                               _build_family("brute_force", db),
                               queries, explain=True)
    notes = [n for n in rec.notes if n.get("op") == "select_k"]
    assert notes, "brute_force search resolved no select_k"
    # the dry-run planner surface agrees with what the search recorded
    note = notes[0]
    plan = select_k_plan(note["n"], note["k"])
    assert plan["algo"] == note["algo"]
    assert plan["k_pad"] == note["k_pad"]


def test_forced_scan_mode_reasons(db, queries):
    idx = ivf_pq.build(db, ivf_pq.IndexParams(n_lists=8, pq_dim=8))
    _, _, rec = ivf_pq.search(
        idx, queries, K, ivf_pq.SearchParams(n_probes=4, scan_mode="lut"),
        explain=True)
    assert rec.engine == "lut" and rec.reason == "forced"
    assert rec.plan["memory_model"] == "lut"
    assert rec.plan["memory_auto"] is False


# ------------------------------------------------ the warn-once satellite

def test_no_verdict_warns_exactly_once(monkeypatch, caplog):
    # fake a TPU backend with a verdict-free probe table: auto must
    # route XLA with reason no_fused_wins_verdict and say so ONCE
    monkeypatch.setattr(pk.jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(pk, "_fused_verdict", lambda family: None)
    pk._reset_fused_warn()
    with caplog.at_level(logging.WARNING,
                         logger="raft_tpu.ops.pallas_kernels"):
        for family in ("brute_force", "ivf_flat", "ivf_pq"):
            use_fused, interp, reason = pk.fused_dispatch_explained(
                family, "auto")
            assert (use_fused, interp) == (False, False)
            assert reason == "no_fused_wins_verdict"
    warnings = [r for r in caplog.records
                if "fused_wins" in r.getMessage()]
    assert len(warnings) == 1, [r.getMessage() for r in warnings]
    assert "pallas_probe" in warnings[0].getMessage()
    pk._reset_fused_warn()


def test_measured_loss_does_not_warn(monkeypatch, caplog):
    # a measured fused_loses verdict is routing policy, not a gap —
    # silent by design
    monkeypatch.setattr(pk.jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(pk, "_fused_verdict", lambda family: False)
    pk._reset_fused_warn()
    with caplog.at_level(logging.WARNING,
                         logger="raft_tpu.ops.pallas_kernels"):
        assert pk.fused_dispatch_explained("brute_force", "auto") == (
            False, False, "fused_loses")
        assert pk.fused_dispatch_explained("ivf_flat", "auto")[2] == \
            "fused_loses"
    assert not [r for r in caplog.records
                if "fused_wins" in r.getMessage()]


def test_auto_fused_wins_on_verdict(monkeypatch):
    monkeypatch.setattr(pk.jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(pk, "_fused_verdict", lambda family: True)
    assert pk.fused_dispatch_explained("ivf_pq", "auto") == (
        True, False, "auto_fused_wins")


def test_dispatch_counts_reads_custom_registry():
    reg = obm.Registry()
    ctr = reg.counter("raft_tpu_dispatch_total", "test",
                      ("family", "engine", "reason"))
    ctr.labels("brute_force", "xla", "tpu_absent").inc(3)
    counts = obs_explain.dispatch_counts(registry=reg)
    assert counts == {("brute_force", "xla", "tpu_absent"): 3}
