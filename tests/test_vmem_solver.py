"""Adversarial coverage for ``core.resources.solve_vmem_tiles`` — the
solver every fused-kernel tile planner (and graftcheck Tier K's VMEM
sweep) leans on. The invariants pinned here:

* alignment: ``outer`` is always an 8-multiple in [8, outer_cap],
  ``inner`` a 128-multiple (or the full rounded extent);
* the (8, 128) floor: degenerate budgets (zero, negative, fixed term
  swallowing everything) degrade to exactly one aligned cell rather
  than crashing or returning zero-sized tiles — the kernel still runs,
  the budget becomes a target;
* budget honesty: whenever the solver returns anything *above* the
  floor, the affine cost model it advertises is actually satisfied;
* non-divisor extents: ``inner_max`` is rounded UP to lane alignment,
  never truncated to zero.
"""

import numpy as np
import pytest

from raft_tpu.core.resources import solve_vmem_tiles


def _cost(o, i, cell, outer_b, inner_b):
    return o * outer_b + i * inner_b + o * i * cell


# --------------------------------------------------- degenerate budgets

@pytest.mark.parametrize("budget", [0, -1, -(1 << 40), 1])
def test_degenerate_budget_degrades_to_aligned_floor(budget):
    assert solve_vmem_tiles(budget, cell_bytes=12, outer_bytes=512,
                            inner_bytes=516, inner_max=4096) == (8, 128)


def test_fixed_bytes_swallowing_the_budget_degrades_not_crashes():
    out = solve_vmem_tiles(12 << 20, cell_bytes=12, outer_bytes=512,
                           inner_bytes=516, inner_max=4096,
                           fixed_bytes=13 << 20)
    assert out == (8, 128)


def test_single_aligned_cell_over_budget_still_returns_the_floor():
    # one (8, 128) cell costs more than the whole budget: the solver
    # must still hand back the floor, never (0, anything)
    out = solve_vmem_tiles(1024, cell_bytes=1 << 20, outer_bytes=0,
                           inner_bytes=0, inner_max=128)
    assert out == (8, 128)


# ----------------------------------------------- non-divisor inner extents

@pytest.mark.parametrize("inner_max,expect", [
    (1, 128), (100, 128), (129, 256), (1000, 1024), (4096, 4096),
])
def test_inner_max_rounds_up_to_lane_alignment(inner_max, expect):
    outer, inner = solve_vmem_tiles(1 << 30, cell_bytes=4, outer_bytes=4,
                                    inner_bytes=4, inner_max=inner_max)
    assert inner == expect
    assert outer % 8 == 0 and outer >= 8


def test_zero_inner_max_is_clamped_to_one_cell():
    outer, inner = solve_vmem_tiles(1 << 30, cell_bytes=4, outer_bytes=4,
                                    inner_bytes=4, inner_max=0)
    assert inner == 128


# ------------------------------------------------------- budget honesty

def test_full_extent_solution_fits_the_budget():
    budget = 12 << 20
    cell, outer_b, inner_b, inner_max = 12, 544, 516, 2048
    outer, inner = solve_vmem_tiles(budget, cell, outer_b, inner_b,
                                    inner_max)
    assert inner == inner_max  # already lane-aligned: full-extent branch
    assert outer == 256  # generous budget: outer rides up to the cap
    assert _cost(outer, inner, cell, outer_b, inner_b) <= budget


def test_inner_tiled_solution_fits_the_budget():
    # force the tiled branch: full extent too wide for 8 outer rows
    budget = 1 << 20
    cell, outer_b, inner_b, inner_max = 64, 1024, 2048, 1 << 16
    outer, inner = solve_vmem_tiles(budget, cell, outer_b, inner_b,
                                    inner_max)
    assert outer == 8 and inner % 128 == 0
    assert _cost(outer, inner, cell, outer_b, inner_b) <= budget


def test_outer_cap_is_honored():
    outer, _ = solve_vmem_tiles(1 << 40, cell_bytes=1, outer_bytes=1,
                                inner_bytes=1, inner_max=128,
                                outer_cap=64)
    assert outer == 64


# ------------------------------------------------- randomized invariants

def test_randomized_alignment_and_budget_invariants():
    rng = np.random.default_rng(0xA11)
    for _ in range(500):
        budget = int(rng.integers(-(1 << 20), 1 << 26))
        cell = int(rng.integers(0, 1 << 12))
        outer_b = int(rng.integers(0, 1 << 14))
        inner_b = int(rng.integers(0, 1 << 14))
        inner_max = int(rng.integers(0, 1 << 16))
        fixed = int(rng.integers(0, 1 << 24))
        outer, inner = solve_vmem_tiles(budget, cell, outer_b, inner_b,
                                        inner_max, fixed_bytes=fixed)
        args = (budget, cell, outer_b, inner_b, inner_max, fixed)
        # alignment invariants hold unconditionally
        assert outer % 8 == 0 and 8 <= outer <= 256, args
        assert inner % 128 == 0 and inner >= 128, args
        assert inner <= max(inner_max + (-inner_max) % 128, 128), args
        # above the floor, the advertised cost model is satisfied
        if (outer, inner) != (8, 128):
            have = max(budget - fixed, 1)
            assert _cost(outer, inner, cell, outer_b, inner_b) <= have, args
        # pure: same inputs, same answer
        assert solve_vmem_tiles(budget, cell, outer_b, inner_b, inner_max,
                                fixed_bytes=fixed) == (outer, inner), args
