"""SLO burn-rate monitoring (docs/observability.md "SLO catalog").

The contract under test: burn math per kind (availability, latency_p99,
recall_floor) against a live registry, window re-baselining, fast-burn
firing exactly once per excursion, engine label isolation, the ``/slo``
endpoint on a running engine's MetricsServer, and the fast-burn →
flight-recorder auto-dump wiring.
"""

import json
import math
import urllib.error
import urllib.request

import numpy as np
import pytest

from raft_tpu import serving
from raft_tpu.neighbors import ivf_flat
from raft_tpu.obs import metrics as obm
from raft_tpu.obs.quality import OnlineRecallEstimator
from raft_tpu.obs.slo import SLO, SLOMonitor
from raft_tpu.serving.stats import ServingStats

pytestmark = pytest.mark.fast

DIM = 16
K = 5


# ----------------------------------------------------------- declaration

def test_slo_declaration_validates():
    with pytest.raises(ValueError, match="kind"):
        SLO("x", "latency_p50", 0.99, threshold_ms=10.0)
    with pytest.raises(ValueError, match="objective"):
        SLO("x", "availability", 1.0)
    with pytest.raises(ValueError, match="threshold_ms"):
        SLO("x", "latency_p99", 0.99)
    with pytest.raises(ValueError, match="duplicate"):
        SLOMonitor([SLO("a", "availability", 0.999),
                    SLO("a", "availability", 0.99)], "e",
                   registry=obm.Registry())


# ------------------------------------------------------------- burn math

def _stats_and_monitor(slos, clock=None, window_s=300.0):
    reg = obm.Registry()
    st = ServingStats(registry=reg, engine_label="eng-a")
    mon = SLOMonitor(slos, "eng-a", registry=reg, window_s=window_s,
                     clock=clock or (lambda: 0.0))
    return reg, st, mon


def _complete(st, n, total_s=0.001):
    st.record_batch(n, 8, [0.0] * n, total_s, [total_s] * n)


def test_availability_burn_is_windowed_error_rate_over_budget():
    slo = SLO("avail", "availability", 0.999)
    reg, st, mon = _stats_and_monitor([slo])
    assert mon.burn_rate(slo) == 0.0  # no traffic: no alert on silence
    _complete(st, 90)
    st.record_batch_failed(10)
    # 10% bad over a 0.1% budget -> burning 100x
    assert mon.burn_rate(slo) == pytest.approx(100.0)
    # cancelled is a client verdict, not a serving failure
    st.record_cancelled(50)
    assert mon.burn_rate(slo) == pytest.approx(100.0)
    # another engine's failures on the SAME registry do not count
    other = ServingStats(registry=reg, engine_label="eng-b")
    other.record_batch_failed(500)
    assert mon.burn_rate(slo) == pytest.approx(100.0)


def test_availability_counts_sheds_and_rejections_as_bad():
    slo = SLO("avail", "availability", 0.99)
    _, st, mon = _stats_and_monitor([slo])
    _complete(st, 96)
    st.record_shed_deadline(2)
    st.record_rejected("overload")
    st.record_rejected("breaker")
    # 4 bad / 100 total over a 1% budget -> 4x
    assert mon.burn_rate(slo) == pytest.approx(4.0)


def test_latency_burn_from_histogram_tail():
    fast = SLO("lat", "latency_p99", 0.99, threshold_ms=60_000.0)
    _, st, mon = _stats_and_monitor([fast])
    _complete(st, 50, total_s=0.05)
    assert mon.burn_rate(fast) == 0.0  # nothing near a 60 s threshold

    slow = SLO("lat", "latency_p99", 0.99, threshold_ms=0.1)
    _, st, mon = _stats_and_monitor([slow])
    _complete(st, 50, total_s=0.05)  # every request far over 0.1 ms
    # ~100% over-threshold against a 1% allowance -> ~100x burn
    assert mon.burn_rate(slow) == pytest.approx(100.0, rel=0.05)


def test_recall_floor_burn_tracks_worst_window():
    slo = SLO("recall", "recall_floor", 0.95)
    reg, _, mon = _stats_and_monitor([slo])
    assert mon.burn_rate(slo) == 0.0  # no shadow samples yet: silence
    est = OnlineRecallEstimator(registry=reg)
    est.observe("ivf_flat", K, 8, 1.0)
    assert mon.burn_rate(slo) == 0.0
    est.observe("ivf_pq", K, 8, 0.8)  # the worst window drives the burn
    assert mon.burn_rate(slo) == pytest.approx((1 - 0.8) / 0.05)


def test_window_roll_rebaselines_counters():
    t = [0.0]
    slo = SLO("avail", "availability", 0.999)
    _, st, mon = _stats_and_monitor([slo], clock=lambda: t[0],
                                    window_s=300.0)
    _complete(st, 90)
    st.record_batch_failed(10)
    assert mon.burn_rate(slo) == pytest.approx(100.0)
    t[0] = 301.0  # window expires: the old failures age out
    assert mon.burn_rate(slo) == 0.0
    st.record_batch_failed(1)  # fresh window, fresh budget
    _complete(st, 99)
    assert mon.burn_rate(slo) == pytest.approx(10.0)


def test_broken_fast_burn_callback_is_counted_not_raised():
    # graftcheck F003 regression: a pager hook that raises must neither
    # fail the scrape path nor vanish — it lands in the registry
    slo = SLO("avail", "availability", 0.999, fast_burn=14.0)
    reg = obm.Registry()
    st = ServingStats(registry=reg, engine_label="eng-a")

    def broken_hook(name, burn):
        raise RuntimeError("pager misconfigured")

    mon = SLOMonitor([slo], "eng-a", registry=reg, window_s=300.0,
                     on_fast_burn=broken_hook)
    _complete(st, 90)
    st.record_batch_failed(10)
    burn = mon.burn_rate(slo)  # crossing fires the hook; must not raise
    assert burn >= 14.0
    fam = reg.get("raft_tpu_slo_callback_errors_total")
    assert fam is not None
    counts = {labels: child.value for labels, child in fam.collect()}
    assert counts[("eng-a", "avail")] == 1


def test_fast_burn_fires_once_per_excursion():
    t = [0.0]
    fired = []
    slo = SLO("avail", "availability", 0.999, fast_burn=14.0)
    reg = obm.Registry()
    st = ServingStats(registry=reg, engine_label="eng-a")
    mon = SLOMonitor([slo], "eng-a", registry=reg, window_s=300.0,
                     clock=lambda: t[0],
                     on_fast_burn=lambda name, burn: fired.append(
                         (name, burn)))
    _complete(st, 90)
    st.record_batch_failed(10)
    for _ in range(5):  # scrapes repeat; the dump must not
        mon.burn_rate(slo)
    assert len(fired) == 1
    assert fired[0][0] == "avail" and fired[0][1] >= 14.0
    t[0] = 301.0
    assert mon.burn_rate(slo) == 0.0  # burn drops: excursion re-arms
    st.record_batch_failed(10)
    _complete(st, 90)
    mon.burn_rate(slo)
    assert len(fired) == 2


def test_burn_gauges_export_on_the_registry():
    slo = SLO("avail", "availability", 0.999)
    reg, st, mon = _stats_and_monitor([slo])
    _complete(st, 99)
    st.record_batch_failed(1)
    burn = {k: c.value
            for k, c in reg.get("raft_tpu_slo_burn_rate").collect()}
    budget = {k: c.value
              for k, c in reg.get("raft_tpu_slo_budget_remaining").collect()}
    assert burn[("eng-a", "avail")] == pytest.approx(10.0)
    assert budget[("eng-a", "avail")] == 0.0


# ------------------------------------------------- engine + /slo endpoint

@pytest.fixture(scope="module")
def flat_index():
    rng = np.random.default_rng(3)
    db = rng.standard_normal((1500, DIM)).astype(np.float32)
    return ivf_flat.build(db, ivf_flat.IndexParams(n_lists=16))


@pytest.fixture()
def searcher(flat_index):
    return serving.ivf_flat_searcher(flat_index,
                                     ivf_flat.SearchParams(n_probes=8))


def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=5) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def _q(rng):
    return rng.standard_normal(DIM).astype(np.float32)


def test_slo_endpoint_404_without_monitor(searcher):
    cfg = serving.EngineConfig(max_batch=8, warm_ks=(K,), metrics_port=0)
    with serving.Engine(searcher, cfg) as eng:
        assert _get(eng.metrics_server.url + "/slo")[0] == 404


def test_slo_endpoint_and_fast_burn_auto_dump(searcher):
    rng = np.random.default_rng(4)
    # an oracle that never agrees with the served answer: recall 0.0,
    # so the recall_floor SLO burns at (1-0)/(1-0.95) = 20x >= 14
    def hostile_oracle(qs, k):
        n = np.asarray(qs).shape[0]
        return np.zeros((n, k)), np.full((n, k), 1499, np.int64)

    cfg = serving.EngineConfig(
        max_batch=8, max_wait_us=5000, warm_ks=(K,), metrics_port=0,
        hang_timeout_s=None,
        registry=obm.Registry(),  # isolate the recall gauge family
        shadow_oracle=hostile_oracle, shadow_sample_rate=1.0,
        shadow_deadline_ms=30_000.0,
        slos=(SLO("recall", "recall_floor", 0.95),
              SLO("avail", "availability", 0.999)))
    with serving.Engine(searcher, cfg) as eng:
        for _ in range(8):
            eng.search(_q(rng), K)
        eng.drain(60)
        # wait for the shadow worker to grade at least one sample
        eng.shadow.close()
        url = eng.metrics_server.url
        code, body = _get(url + "/slo")
        assert code == 200
        doc = json.loads(body)
        assert doc["engine"] == eng.stats.engine_label
        rows = {r["name"]: r for r in doc["slos"]}
        assert rows["avail"]["burn_rate"] == 0.0
        assert rows["avail"]["budget_remaining"] == 1.0
        recall = rows["recall"]
        assert recall["worst_recall"] == 0.0
        assert recall["burn_rate"] == pytest.approx(20.0)
        assert recall["fast_burn"] is True
        # the crossing froze a flight-recorder bundle, exactly once
        assert eng.last_diagnostics is not None
        assert eng.last_diagnostics["reason"] == "slo_fast_burn"
        n_dumps = eng.stats.registry.get(
            "raft_tpu_serving_diagnostics_dumps_total")
        dumps = {k: c.value for k, c in n_dumps.collect()}
        assert dumps[(eng.stats.engine_label, "slo_fast_burn")] == 1.0
        _get(url + "/slo")  # still burning: no second dump (one excursion)
        dumps = {k: c.value for k, c in n_dumps.collect()}
        assert dumps[(eng.stats.engine_label, "slo_fast_burn")] == 1.0
        # the burn gauges ride the normal scrape too
        code, text = _get(url + "/metrics")
        assert code == 200
        e = eng.stats.engine_label
        assert f'raft_tpu_slo_burn_rate{{engine="{e}",slo="recall"}}' \
            in text


def test_recall_floor_burn_is_nan_safe(searcher):
    # an engine with a recall SLO but shadow sampling OFF must report
    # burn 0 (never alert on silence), not NaN-poison the scrape
    cfg = serving.EngineConfig(
        max_batch=8, warm_ks=(K,), metrics_port=0,
        registry=obm.Registry(),  # other tests' recall windows must not
        slos=(SLO("recall", "recall_floor", 0.95),))  # bleed in here
    with serving.Engine(searcher, cfg) as eng:
        code, body = _get(eng.metrics_server.url + "/slo")
        assert code == 200
        (row,) = json.loads(body)["slos"]
        assert row["burn_rate"] == 0.0 and "worst_recall" not in row
        assert not math.isnan(row["burn_rate"])
