"""API-surface guard: the pylibraft-parity names and the PARITY.md claims
must keep importing (the analog of the reference's test_doctests.py, which
exercises every public module's docstring surface)."""

import importlib
import pkgutil

import pytest


def _discover_modules():
    """All importable raft_tpu modules, found on disk (no drift as modules
    are added)."""
    import raft_tpu

    names = ["raft_tpu"]
    for info in pkgutil.walk_packages(raft_tpu.__path__, "raft_tpu."):
        # the ctypes-loaded C library is not a Python module
        if "libraft_tpu_native" in info.name:
            continue
        names.append(info.name)
    return sorted(names)


# explicit floor: if discovery somehow regresses, these must still be seen
MODULES = [
    "raft_tpu",
    "raft_tpu.core",
    "raft_tpu.core.bitset",
    "raft_tpu.core.errors",
    "raft_tpu.core.interruptible",
    "raft_tpu.core.logger",
    "raft_tpu.core.operators",
    "raft_tpu.core.resources",
    "raft_tpu.core.resources_manager",
    "raft_tpu.core.serialize",
    "raft_tpu.core.tracing",
    "raft_tpu.ops",
    "raft_tpu.ops.distance",
    "raft_tpu.ops.fused_l2_nn",
    "raft_tpu.ops.kernels",
    "raft_tpu.ops.linalg",
    "raft_tpu.ops.matrix",
    "raft_tpu.ops.pallas_kernels",
    "raft_tpu.ops.rng",
    "raft_tpu.ops.select_k",
    "raft_tpu.sparse",
    "raft_tpu.sparse.convert",
    "raft_tpu.sparse.distance",
    "raft_tpu.sparse.linalg",
    "raft_tpu.sparse.mst",
    "raft_tpu.sparse.neighbors",
    "raft_tpu.sparse.op",
    "raft_tpu.sparse.selection",
    "raft_tpu.sparse.solver",
    "raft_tpu.sparse.spectral",
    "raft_tpu.cluster",
    "raft_tpu.cluster.kmeans",
    "raft_tpu.cluster.kmeans_balanced",
    "raft_tpu.cluster.single_linkage",
    "raft_tpu.neighbors",
    "raft_tpu.neighbors.ball_cover",
    "raft_tpu.neighbors.brute_force",
    "raft_tpu.neighbors.cagra",
    "raft_tpu.neighbors.epsilon_neighborhood",
    "raft_tpu.neighbors.hnsw",
    "raft_tpu.neighbors.ivf_flat",
    "raft_tpu.neighbors.ivf_pq",
    "raft_tpu.neighbors.nn_descent",
    "raft_tpu.neighbors.rbc",
    "raft_tpu.neighbors.refine",
    "raft_tpu.parallel",
    "raft_tpu.parallel.comms",
    "raft_tpu.parallel.sharded",
    "raft_tpu.stats",
    "raft_tpu.bench",
    "raft_tpu.bench.export",
    "raft_tpu.bench.prims",
    "raft_tpu.bench.runner",
    "raft_tpu.native",
    "raft_tpu.common",
    "raft_tpu.distance",
    "raft_tpu.label",
    "raft_tpu.matrix",
    "raft_tpu.random",
    "raft_tpu.solver",
    "raft_tpu.spatial",
    "raft_tpu.utils",
    "raft_tpu.utils.compile_cache",
    "raft_tpu.utils.shape",
]


@pytest.mark.parametrize("mod", sorted(set(MODULES) | set(_discover_modules())))
def test_module_imports(mod):
    importlib.import_module(mod)


def test_discovery_covers_floor():
    assert set(MODULES) <= set(_discover_modules())


def test_pylibraft_parity_names():
    """Names a pylibraft user would reach for (SURVEY.md §2.10)."""
    from raft_tpu.common import DeviceResources, device_ndarray  # noqa: F401
    from raft_tpu.distance import (  # noqa: F401
        DistanceType, pairwise_distance, fused_l2_nn_argmin)
    from raft_tpu.matrix import select_k  # noqa: F401
    from raft_tpu.random import rmat, make_blobs  # noqa: F401
    from raft_tpu.cluster.kmeans import (  # noqa: F401
        KMeansParams, fit, fit_predict, cluster_cost, compute_new_centroids)
    from raft_tpu.neighbors.ivf_pq import (  # noqa: F401
        IndexParams, SearchParams, build, extend, search, serialize,
        deserialize)
    from raft_tpu.neighbors.cagra import build as cagra_build  # noqa: F401
    from raft_tpu.neighbors.hnsw import from_cagra  # noqa: F401
    from raft_tpu.neighbors.refine import refine  # noqa: F401
    from raft_tpu.neighbors.brute_force import knn  # noqa: F401


def test_comms_t_surface():
    """The comms_t method set (core/comms.hpp:127-661)."""
    from raft_tpu.parallel.comms import Comms

    for name in ("allreduce", "allgather", "allgatherv", "gather", "gatherv",
                 "bcast", "reduce", "reducescatter", "alltoall", "ppermute",
                 "shift", "device_send_recv", "device_multicast_sendrecv",
                 "comm_split", "sync", "rank", "size", "run", "shard"):
        assert hasattr(Comms, name), name


def test_round4_surface_names():
    """Round-4 additions stay public: SCREEN select, sharded
    checkpoint/resume, the native hnsw-role ef-search, config scaling."""
    from raft_tpu.bench.runner import scale_config  # noqa: F401
    from raft_tpu.native import graph_greedy_search  # noqa: F401
    from raft_tpu.ops.select_k import SelectAlgo
    from raft_tpu.parallel.sharded import (  # noqa: F401
        deserialize_ivf_flat, deserialize_ivf_pq, serialize_ivf_flat,
        serialize_ivf_pq)
    from raft_tpu.utils.shape import as_query_array  # noqa: F401

    assert SelectAlgo.SCREEN.value == "screen"


def test_imports_are_deprecation_clean():
    """Importing the full public surface must not raise DeprecationWarning
    (one subprocess so -W error::DeprecationWarning covers import time)."""
    import os
    import subprocess
    import sys

    mods = sorted(set(MODULES) | set(_discover_modules()))
    code = ("import importlib\n"
            "for m in %r:\n"
            "    importlib.import_module(m)\n" % (mods,))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-W", "error::DeprecationWarning", "-c", code],
        capture_output=True, text=True, env=env)
    assert proc.returncode == 0, proc.stderr


def test_no_cross_package_private_imports():
    """R004 as an API-surface invariant: no raft_tpu package reaches
    another package's underscore-private names (the detail:: layering
    convention); enforced by the same analyzer the graftcheck CI gate
    runs, so a local pytest run fails before CI does."""
    import os

    from raft_tpu.analysis import collect_modules
    from raft_tpu.analysis.layering import check_layering

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    modules, parse_errors = collect_modules(repo, dirs=("raft_tpu",))
    assert parse_errors == []
    findings = check_layering(modules)
    assert findings == [], "\n".join(f.format() for f in findings)
