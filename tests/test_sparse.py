"""Sparse layer tests — conversions, linalg, distances, MST, spectral,
single-linkage (reference: cpp/test/sparse/*, cpp/test/cluster/linkage.cu)."""

import numpy as np

import jax
import jax.numpy as jnp

from raft_tpu.sparse import convert, distance, linalg, mst, spectral, types
from raft_tpu.cluster import single_linkage
from raft_tpu.cluster.single_linkage import SingleLinkageParams


def _random_csr(rng, n, m, density=0.2):
    dense = rng.standard_normal((n, m)).astype(np.float32)
    dense[rng.random((n, m)) > density] = 0.0
    nnz = int((dense != 0).sum())
    rows, cols = np.nonzero(dense)
    coo = types.coo_from_arrays(rows, cols, dense[rows, cols], (n, m))
    return dense, convert.coo_to_csr(coo)


def test_conversions_roundtrip(rng):
    dense, csr = _random_csr(rng, 20, 15)
    np.testing.assert_allclose(np.asarray(convert.csr_to_dense(csr)), dense)
    coo = convert.csr_to_coo(csr)
    np.testing.assert_allclose(np.asarray(convert.coo_to_dense(coo)), dense)
    back = convert.coo_to_csr(coo)
    np.testing.assert_allclose(np.asarray(convert.csr_to_dense(back)), dense)


def test_spmm_spmv_sddmm(rng):
    dense, csr = _random_csr(rng, 20, 15)
    b = rng.standard_normal((15, 6)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(linalg.spmm(csr, b)), dense @ b,
                               rtol=1e-4, atol=1e-4)
    v = rng.standard_normal(15).astype(np.float32)
    np.testing.assert_allclose(np.asarray(linalg.spmv(csr, v)), dense @ v,
                               rtol=1e-4, atol=1e-4)
    # sddmm samples A·Bᵀ at structure nnz
    a2 = rng.standard_normal((20, 6)).astype(np.float32)
    b2 = rng.standard_normal((15, 6)).astype(np.float32)
    out = linalg.sddmm(a2, b2, csr)
    full = a2 @ b2.T
    got = np.asarray(convert.csr_to_dense(out))
    want = np.where(dense != 0, full, 0.0)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_degree_norms_transpose(rng):
    dense, csr = _random_csr(rng, 12, 9)
    np.testing.assert_array_equal(np.asarray(linalg.degree(csr)),
                                  (dense != 0).sum(1))
    np.testing.assert_allclose(np.asarray(linalg.row_norm(csr, "l2")),
                               (dense ** 2).sum(1), rtol=1e-5)
    t = linalg.transpose(csr)
    np.testing.assert_allclose(np.asarray(convert.csr_to_dense(t)), dense.T)


def test_sparse_pairwise_and_knn(rng):
    dx, x = _random_csr(rng, 25, 30, 0.3)
    dy, y = _random_csr(rng, 18, 30, 0.3)
    d = np.asarray(distance.pairwise_distance(x, y, "euclidean"))
    want = np.sqrt(((dx[:, None, :] - dy[None, :, :]) ** 2).sum(-1))
    np.testing.assert_allclose(d, want, rtol=1e-3, atol=1e-3)
    # jaccard on binary structure
    dj = np.asarray(distance.pairwise_distance(x, y, "jaccard"))
    bx = dx != 0
    by = dy != 0
    inter = (bx[:, None, :] & by[None, :, :]).sum(-1)
    union = (bx[:, None, :] | by[None, :, :]).sum(-1)
    wantj = 1.0 - inter / np.maximum(union, 1)
    np.testing.assert_allclose(dj, wantj, rtol=1e-5, atol=1e-5)
    vals, idx = distance.knn(x, y, k=3, metric="euclidean")
    np.testing.assert_array_equal(np.asarray(idx), np.argsort(want, 1)[:, :3])


def test_mst_matches_scipy_style(rng):
    # build a random connected graph and check MST weight vs a prim's
    # implementation in numpy
    n = 30
    pts = rng.standard_normal((n, 2)).astype(np.float32)
    full = np.sqrt(((pts[:, None] - pts[None, :]) ** 2).sum(-1))
    # complete graph edge list (both directions, no self)
    rows, cols = np.nonzero(~np.eye(n, dtype=bool))
    coo = types.coo_from_arrays(rows, cols, full[rows, cols], (n, n))
    src, dst, w = mst.mst(coo)
    w = np.asarray(w)
    got_total = w[np.isfinite(w)].sum()
    assert np.isfinite(w).sum() == n - 1

    # prim's reference
    in_tree = np.zeros(n, bool)
    in_tree[0] = True
    best = full[0].copy()
    total = 0.0
    for _ in range(n - 1):
        best[in_tree] = np.inf
        j = int(np.argmin(best))
        total += best[j]
        in_tree[j] = True
        best = np.minimum(best, full[j])
    np.testing.assert_allclose(got_total, total, rtol=1e-5)


def test_mst_disconnected_forest():
    # two triangles, no connection: forest with 4 edges
    rows = np.array([0, 1, 2, 0, 3, 4, 5, 3])
    cols = np.array([1, 2, 0, 2, 4, 5, 3, 5])
    w = np.ones(8, np.float32)
    both_r = np.concatenate([rows, cols])
    both_c = np.concatenate([cols, rows])
    both_w = np.concatenate([w, w])
    coo = types.coo_from_arrays(both_r, both_c, both_w, (6, 6))
    src, dst, wt = mst.mst(coo)
    assert np.isfinite(np.asarray(wt)).sum() == 4


def test_spectral_partition_two_blobs(rng):
    # two dense communities weakly connected
    n = 40
    a = np.zeros((n, n), np.float32)
    a[:20, :20] = rng.random((20, 20)) < 0.5
    a[20:, 20:] = rng.random((20, 20)) < 0.5
    a[0, 20] = a[20, 0] = 1.0
    np.fill_diagonal(a, 0)
    a = np.maximum(a, a.T).astype(np.float32)
    rows, cols = np.nonzero(a)
    csr = convert.coo_to_csr(
        types.coo_from_arrays(rows, cols, a[rows, cols], (n, n)))
    labels, emb = spectral.partition(csr, 2)
    same1 = (labels[:20] == labels[0]).mean()
    same2 = (labels[20:] == labels[20]).mean()
    assert same1 >= 0.9 and same2 >= 0.9
    cut, ratio = spectral.analyze_partition(csr, labels)
    assert cut <= 4.0  # only the weak bridge should be cut


def test_single_linkage_two_moons_style(rng):
    # two well-separated blobs → single linkage splits them perfectly
    a = rng.standard_normal((30, 2)).astype(np.float32)
    b = rng.standard_normal((30, 2)).astype(np.float32) + 20.0
    x = np.concatenate([a, b])
    labels = single_linkage.single_linkage(
        x, SingleLinkageParams(n_clusters=2, connectivity_k=10))
    assert len(np.unique(labels)) == 2
    assert len(np.unique(labels[:30])) == 1
    assert len(np.unique(labels[30:])) == 1


# ---------------------------------------------------------------------------
# sparse.op (reference: sparse/op/{filter,reduce,row_op,slice,sort}.cuh)

def test_coo_remove_scalar_and_zeros(rng):
    from raft_tpu.sparse import COO, op

    rows = np.array([0, 0, 1, 2, 2, 3], np.int32)
    cols = np.array([1, 2, 0, 1, 3, 2], np.int32)
    data = np.array([5.0, 0.0, 3.0, 0.0, 7.0, 2.0], np.float32)
    coo = COO(jnp.asarray(rows), jnp.asarray(cols), jnp.asarray(data), (4, 4))
    out, nnz = op.coo_remove_zeros(coo)
    assert int(nnz) == 4
    got = {(int(r), int(c)): float(v)
           for r, c, v in zip(np.asarray(out.rows)[:4], np.asarray(out.cols)[:4],
                              np.asarray(out.data)[:4])}
    assert got == {(0, 1): 5.0, (1, 0): 3.0, (2, 3): 7.0, (3, 2): 2.0}
    assert (np.asarray(out.rows)[4:] == -1).all()


def test_coo_sum_and_max_duplicates():
    from raft_tpu.sparse import COO, op

    rows = np.array([0, 0, 1, 0], np.int32)
    cols = np.array([1, 1, 2, 1], np.int32)
    data = np.array([1.0, 2.0, 4.0, 3.0], np.float32)
    coo = COO(jnp.asarray(rows), jnp.asarray(cols), jnp.asarray(data), (2, 3))
    s = op.coo_sum_duplicates(coo)
    got = {(int(r), int(c)): float(v)
           for r, c, v in zip(np.asarray(s.rows), np.asarray(s.cols),
                              np.asarray(s.data)) if r >= 0}
    assert got == {(0, 1): 6.0, (1, 2): 4.0}
    m = op.coo_max_duplicates(coo)
    got = {(int(r), int(c)): float(v)
           for r, c, v in zip(np.asarray(m.rows), np.asarray(m.cols),
                              np.asarray(m.data)) if r >= 0}
    assert got == {(0, 1): 3.0, (1, 2): 4.0}


def test_csr_row_ops_and_slice(rng):
    import scipy.sparse as sp
    from raft_tpu.sparse import csr_from_scipy_like, op

    m = sp.random(8, 6, density=0.4, format="csr", random_state=0,
                  dtype=np.float32)
    csr = csr_from_scipy_like(m.indptr, m.indices, m.data, m.shape)
    doubled = op.csr_row_op(csr, lambda rid, vals: vals * 2.0)
    np.testing.assert_allclose(np.asarray(doubled.data), m.data * 2, rtol=1e-6)

    sl = op.csr_row_slice(csr, 2, 5)
    ref = m[2:5]
    assert sl.shape == (3, 6)
    np.testing.assert_array_equal(np.asarray(sl.indptr), ref.indptr)
    np.testing.assert_allclose(np.asarray(sl.data), ref.data, rtol=1e-6)


# ---------------------------------------------------------------------------
# sparse.neighbors (knn.cuh, cross_component_nn.cuh)

def test_sparse_brute_force_knn(rng):
    import scipy.sparse as sp
    from raft_tpu.sparse import csr_from_scipy_like, neighbors as snn

    db_d = rng.standard_normal((50, 20)).astype(np.float32)
    q_d = rng.standard_normal((10, 20)).astype(np.float32)
    db_d[rng.random(db_d.shape) < 0.6] = 0
    q_d[rng.random(q_d.shape) < 0.6] = 0
    db = sp.csr_matrix(db_d)
    q = sp.csr_matrix(q_d)
    d, i = snn.brute_force_knn(
        csr_from_scipy_like(db.indptr, db.indices, db.data, db.shape),
        csr_from_scipy_like(q.indptr, q.indices, q.data, q.shape), 5)
    ref = ((q_d[:, None, :] - db_d[None, :, :]) ** 2).sum(-1)
    np.testing.assert_array_equal(np.asarray(i)[:, 0], ref.argmin(1))


def test_cross_component_nn(rng):
    from raft_tpu.sparse import neighbors as snn

    # two well-separated blobs plus one singleton
    a = rng.standard_normal((10, 4)).astype(np.float32)
    b = rng.standard_normal((8, 4)).astype(np.float32) + 50.0
    x = np.vstack([a, b])
    colors = np.array([0] * 10 + [1] * 8, np.int32)
    d, j = snn.cross_component_nn(x, colors)
    j = np.asarray(j)
    # every point's cross-NN is in the other component
    assert (colors[j[:10]] == 1).all()
    assert (colors[j[10:]] == 0).all()
    full = ((x[:, None, :] - x[None, :, :]) ** 2).sum(-1)
    mask = colors[:, None] == colors[None, :]
    ref = np.where(mask, np.inf, full)
    np.testing.assert_array_equal(j, ref.argmin(1))


# ---------------------------------------------------------------------------
# sparse.solver (sparse/solver/lanczos.cuh)

def test_lanczos_eigsh_smallest():
    import scipy.sparse as sp
    from raft_tpu.sparse import csr_from_scipy_like, solver

    # path-graph laplacian: known smallest eigenvalue 0
    n = 24
    g = sp.diags([-1, 2, -1], [-1, 0, 1], shape=(n, n), format="csr",
                 dtype=np.float32)
    a = csr_from_scipy_like(g.indptr, g.indices, g.data, g.shape)
    vals, vecs = solver.lanczos_eigsh(a, 3, key=jax.random.key(0), ncv=24)
    dense = g.toarray()
    ref = np.linalg.eigvalsh(dense)[:3]
    np.testing.assert_allclose(np.sort(np.asarray(vals)), ref, atol=1e-2)


def test_sparse_selection_select_k(rng):
    import scipy.sparse as sp
    from raft_tpu.sparse import csr_from_scipy_like, selection

    m = sp.random(10, 30, density=0.3, format="csr", random_state=1,
                  dtype=np.float32)
    csr = csr_from_scipy_like(m.indptr, m.indices, m.data, m.shape)
    v, i = selection.select_k(csr, 4, select_min=True)
    dense = m.toarray()
    dense[dense == 0] = np.inf  # stored-entry semantics
    for r in range(10):
        stored = np.sort(dense[r][np.isfinite(dense[r])])[:4]
        got = np.asarray(v[r])[np.isfinite(np.asarray(v[r]))]
        np.testing.assert_allclose(np.sort(got), stored, rtol=1e-6)
