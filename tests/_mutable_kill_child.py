"""Kill -9 victim process for tests/test_mutable.py (not collected by
pytest — module name starts with an underscore).

Opens a ``MutableIvf`` on the directory in ``sys.argv[1]``, applies the
deterministic op stream :func:`make_ops` derives from the seed in
``sys.argv[2]``, and prints ``ACK <lsn>`` (flushed) after each write
returns — i.e. after its WAL frame is fsync-durable. The parent test
reads those lines, SIGKILLs this process at an arbitrary point, and then
proves recovery covers every acknowledged lsn by replaying
``make_ops(seed)[:applied_lsn]`` into a fresh never-crashed writer and
comparing state bit-for-bit.

``sys.argv[3]`` (mode): ``plain`` just writes; ``compact`` also runs an
aggressive background :class:`Compactor` (tiny thresholds, fast poll) so
the kill lands mid-compaction — mid-build, mid-checkpoint, or
mid-publish-window — with realistic probability.

After the stream is exhausted the process parks forever (the parent
always kills it; exiting cleanly would make the test vacuous).
"""

import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

DIM = 8


def make_ops(seed: int, n: int = 64):
    """Deterministic (kind, ids, vectors) stream: adds with explicit
    increasing ids, upserts and deletes of currently-live ids only.
    Op ``i`` commits as lsn ``i + 1``, so a recovered ``applied_lsn``
    of R means exactly ``ops[:R]`` were applied."""
    rng = np.random.RandomState(seed)
    ops = []
    live: list = []
    next_id = 0
    for _ in range(n):
        roll = rng.rand()
        if roll < 0.6 or len(live) < 4:
            count = int(rng.randint(1, 4))
            ids = list(range(next_id, next_id + count))
            next_id += count
            live.extend(ids)
            ops.append(("add", ids, rng.randn(count, DIM)
                        .astype(np.float32)))
        elif roll < 0.85:
            id_ = live[int(rng.randint(len(live)))]
            ops.append(("upsert", [id_], rng.randn(1, DIM)
                        .astype(np.float32)))
        else:
            id_ = live.pop(int(rng.randint(len(live))))
            ops.append(("delete", [id_], None))
    return ops


def apply_op(writer, op):
    kind, ids, vectors = op
    if kind == "add":
        return writer.add(vectors, ids=ids)
    if kind == "upsert":
        return writer.upsert(vectors, ids)
    return writer.delete(ids)


def main():
    from raft_tpu.neighbors import mutable

    directory, seed, mode = sys.argv[1], int(sys.argv[2]), sys.argv[3]
    writer = mutable.MutableIvf(directory, dim=DIM, group_window_s=0.0)
    comp = None
    if mode == "compact":
        comp = mutable.Compactor(writer, delta_threshold=8,
                                 tombstone_ratio=0.05, poll_s=0.005,
                                 min_rows=1)
        comp.start()
    for op in make_ops(seed):
        apply_op(writer, op)
        print(f"ACK {writer.applied_lsn}", flush=True)
    print("DONE", flush=True)
    while True:  # park until the parent kills us
        time.sleep(0.5)


if __name__ == "__main__":
    main()
