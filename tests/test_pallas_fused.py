"""Fused Pallas scan+select (``scan_mode="pallas"``) — interpret-mode
parity, VMEM planner properties, and engine dispatch.

Every kernel test forces TINY tiles so the running top-k carry crosses
the merge boundary (several inner grid steps revisit the output block)
and uses ragged extents so the padded tails exercise the +inf/-1
sentinel path. References are plain numpy. Dispatch tests drive the
public search APIs: on CPU ``scan_mode="pallas"`` must silently fall
back to XLA; with RAFT_TPU_PALLAS_INTERPRET=1 it must route through the
Mosaic interpreter and epsilon-match the XLA engines end to end.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from raft_tpu.neighbors import brute_force, ivf_flat, ivf_pq
from raft_tpu.ops import pallas_kernels as pk


@pytest.fixture(scope="module", autouse=True)
def _drop_interpret_executables():
    """Interpret-mode pallas_call lowers to very large XLA:CPU programs;
    keeping their executables cached for the rest of the session pushes
    the LLVM JIT into its known environment-level segfault a few hundred
    tests later. Drop them (and everything else — later modules recompile
    their own shapes anyway) when this module is done."""
    yield
    jax.clear_caches()


@pytest.fixture()
def rng():
    return np.random.default_rng(0)


def _np_topk(d, k):
    """Ascending (values, ids) per row; +inf / -1 past the row's extent."""
    m, n = d.shape
    order = np.argsort(d, axis=1, kind="stable")[:, :k]
    vals = np.take_along_axis(d, order, axis=1)
    if k > n:
        pad = np.full((m, k - n), np.inf, d.dtype)
        vals = np.concatenate([vals, pad], axis=1)
        order = np.concatenate(
            [order, np.full((m, k - n), -1, order.dtype)], axis=1)
    return vals, order


def _assert_topk_match(v, i, ref_d, k, atol=1e-4):
    """Sorted-value parity + id consistency (ties at the k boundary may
    reorder ids between engines, so id equality is checked through the
    distance each id maps back to, not positionally)."""
    v = np.asarray(v)
    i = np.asarray(i)
    ref_v, _ = _np_topk(ref_d, k)
    np.testing.assert_allclose(v, ref_v, rtol=1e-4, atol=atol)
    valid = i >= 0
    rows, cols = np.nonzero(valid)
    picked = ref_d[rows, i[rows, cols]]
    np.testing.assert_allclose(v[valid], picked, rtol=1e-4, atol=atol)
    assert np.all(v[~valid] == np.inf)


# ------------------------------------------------------------ VMEM planner

def test_solve_vmem_tiles_respects_budget():
    from raft_tpu.core.resources import solve_vmem_tiles

    budget = 12 << 20
    for cell, ob, ib, imax in [(12, 600, 516, 1024), (4, 4096, 8, 131072),
                               (12, 33000, 516, 256)]:
        outer, inner = solve_vmem_tiles(budget, cell, ob, ib, imax)
        assert outer % 8 == 0 and inner % 128 == 0
        if (outer, inner) != (8, 128):  # degraded floor is best-effort
            assert outer * ob + inner * ib + outer * inner * cell <= budget


@pytest.mark.parametrize("m,n,dim,k", [
    (10_000, 1_000_000, 128, 100), (100, 300, 16, 10), (8, 128, 8, 1)])
def test_plan_fused_topk_tiles_fit_vmem(m, n, dim, k):
    tm, tn = pk.plan_fused_topk_tiles(m, n, dim, k)
    assert tm % 8 == 0 and tn % 128 == 0
    assert pk.fused_topk_tile_bytes(tm, tn, dim, k) <= pk.DEFAULT_VMEM_BUDGET
    assert pk.fused_topk_tile_bytes(tm, tn, dim, k) <= pk.VMEM_LIMIT_BYTES


@pytest.mark.parametrize("list_pad", [7, 24, 1000, 1464])
def test_plan_fused_ivf_tile_divides_layout(list_pad):
    for itemsize in (2, 4):
        pt = pk.plan_fused_ivf_tile(list_pad, 128, 100, itemsize)
        assert list_pad % pt == 0
        assert (pk.fused_ivf_vmem_bytes(pt, 128, 100, itemsize)
                <= pk.DEFAULT_VMEM_BUDGET or pt == 1)
    # the sift-1M slab fits whole: one DMA per probe, no inner axis
    assert pk.plan_fused_ivf_tile(1464, 128, 100, 4) == 1464


@pytest.mark.parametrize("list_pad", [16, 24, 1464])
def test_plan_fused_pq_tile_divides_layout(list_pad):
    pt = pk.plan_fused_pq_tile(list_pad, 64, 256, 2, 100)
    assert list_pad % pt == 0
    assert (pk.fused_pq_vmem_bytes(pt, 64, 256, 2, 100)
            <= pk.DEFAULT_VMEM_BUDGET or pt == 1)


def test_fused_workspace_accounting_positive():
    assert pk.fused_topk_workspace_bytes(100, 1000, 32, 10) > 0
    assert pk.fused_ivf_workspace_bytes(16, 4, 32, 8, 24, 10) > 0
    assert pk.fused_pq_workspace_bytes(16, 4, 32, 8, 24, 8, 256, 4, 10) > 0


# --------------------------------------------- fused_l2_topk (brute force)

@pytest.mark.parametrize("k", [1, 10, 64])
def test_fused_l2_topk_parity(rng, k):
    # tn=128 over n=300 → three db tiles: the carry merges twice
    x = rng.standard_normal((23, 16)).astype(np.float32)
    y = rng.standard_normal((300, 16)).astype(np.float32)
    v, i = pk.fused_l2_topk(x, y, k, tm=8, tn=128, interpret=True)
    d = ((x[:, None, :] - y[None, :, :]) ** 2).sum(-1)
    _assert_topk_match(v, i, d, k)


def test_fused_l2_topk_k_exceeds_rows(rng):
    # k > n: the tail of the carry stays at the +inf / -1 sentinels
    x = rng.standard_normal((9, 8)).astype(np.float32)
    y = rng.standard_normal((20, 8)).astype(np.float32)
    v, i = pk.fused_l2_topk(x, y, 64, tm=8, tn=128, interpret=True)
    d = ((x[:, None, :] - y[None, :, :]) ** 2).sum(-1)
    _assert_topk_match(v, i, d, 64)
    assert np.all(np.asarray(i)[:, 20:] == -1)


def test_fused_l2_topk_rejects_large_k(rng):
    with pytest.raises(ValueError, match="small-k"):
        pk.fused_l2_topk(np.zeros((8, 8), np.float32),
                         np.zeros((8, 8), np.float32), 2000)


# ------------------------------------------------ fused_ivf_topk (flat/pq)

def _ivf_ref(probes, qres, list_data, row_norms, ids, clamp):
    """Per-query candidate distances over probed slabs, -1 slots → +inf."""
    nq, P = probes.shape
    pad = list_data.shape[1]
    d = np.full((nq, P * pad), np.inf, np.float32)
    gid = np.full((nq, P * pad), -1, np.int64)
    for qi in range(nq):
        for pj in range(P):
            sl = probes[qi, pj]
            qn = (qres[qi, pj].astype(np.float32) ** 2).sum()
            dots = list_data[sl].astype(np.float32) @ qres[qi, pj]
            dist = qn + row_norms[sl] - 2.0 * dots
            if clamp:
                dist = np.maximum(dist, 0.0)
            dist = np.where(ids[sl] < 0, np.inf, dist)
            d[qi, pj * pad:(pj + 1) * pad] = dist
            gid[qi, pj * pad:(pj + 1) * pad] = ids[sl]
    return d, gid


def _assert_ivf_match(v, i, ref_d, ref_gid, k, atol=1e-4):
    v, i = np.asarray(v), np.asarray(i)
    order = np.argsort(ref_d, axis=1, kind="stable")[:, :k]
    ref_v = np.take_along_axis(ref_d, order, axis=1)
    np.testing.assert_allclose(np.where(v == np.inf, np.inf, v), ref_v,
                               rtol=1e-4, atol=atol)
    # ids map back to a distance the candidate set actually holds for
    # them (a slab probed twice contributes the same id at DIFFERENT
    # residual distances — any of its copies is a valid pairing)
    for qi in range(v.shape[0]):
        lut = {}
        for dist, g in zip(ref_d[qi], ref_gid[qi]):
            if g >= 0:
                lut.setdefault(g, []).append(dist)
        for dist, g in zip(v[qi], i[qi]):
            if g < 0:
                assert dist == np.inf
            else:
                assert any(abs(c - dist) <= atol + 1e-4 * abs(dist)
                           for c in lut[g])


@pytest.mark.parametrize("k", [1, 10])
def test_fused_ivf_topk_parity_carry_boundary(rng, k):
    # pad_tile=8 over list_pad=24 → three slab tiles per probe
    L, pad, rot, nq, P = 6, 24, 16, 5, 3
    data = rng.standard_normal((L, pad, rot)).astype(np.float32)
    ids = np.arange(L * pad, dtype=np.int32).reshape(L, pad)
    ids[:, -5:] = -1  # ragged tails: unfilled slots
    norms = (data.astype(np.float32) ** 2).sum(-1)
    probes = rng.integers(0, L, (nq, P)).astype(np.int32)
    qres = rng.standard_normal((nq, P, rot)).astype(np.float32)
    qn = (qres ** 2).sum(-1)
    v, i = pk.fused_ivf_topk(probes, qres, qn, data, norms, ids, k,
                             pad_tile=8, clamp=True, interpret=True)
    ref_d, ref_gid = _ivf_ref(probes, qres, data, norms, ids, clamp=True)
    _assert_ivf_match(v, i, ref_d, ref_gid, k)


def test_fused_ivf_topk_bf16_cache_fp32_accum(rng):
    # bf16 slab upcast in-kernel, fp32 accumulation (the pq scan cache)
    L, pad, rot, nq, P, k = 4, 16, 8, 4, 2, 6
    data32 = rng.standard_normal((L, pad, rot)).astype(np.float32)
    data = data32.astype(jnp.bfloat16)
    ids = np.arange(L * pad, dtype=np.int32).reshape(L, pad)
    norms = (np.asarray(data, np.float32) ** 2).sum(-1)
    probes = rng.integers(0, L, (nq, P)).astype(np.int32)
    qres = rng.standard_normal((nq, P, rot)).astype(np.float32)
    qn = (qres ** 2).sum(-1)
    v, i = pk.fused_ivf_topk(probes, qres, qn, data, norms, ids, k,
                             pad_tile=8, clamp=False, interpret=True)
    ref_d, ref_gid = _ivf_ref(probes, np.asarray(qres),
                              np.asarray(data, np.float32), norms, ids,
                              clamp=False)
    _assert_ivf_match(v, i, ref_d, ref_gid, k, atol=5e-2)


def test_fused_ivf_topk_rejects_non_divisor_tile(rng):
    L, pad, rot = 2, 24, 8
    data = np.zeros((L, pad, rot), np.float32)
    with pytest.raises(ValueError, match="does not divide"):
        pk.fused_ivf_topk(np.zeros((1, 1), np.int32),
                          np.zeros((1, 1, rot), np.float32),
                          np.zeros((1, 1), np.float32), data,
                          np.zeros((L, pad), np.float32),
                          np.zeros((L, pad), np.int32), 4, pad_tile=7,
                          interpret=True)


# ------------------------------------------------- fused_pq_topk (lut)

def test_fused_pq_topk_parity(rng):
    L, pad, pq_dim, book, pq_len, nq, P, k = 4, 16, 4, 16, 2, 3, 2, 5
    rot = pq_dim * pq_len
    centers = rng.standard_normal((L, rot)).astype(np.float32)
    q_rot = rng.standard_normal((nq, rot)).astype(np.float32)
    cb = rng.standard_normal((pq_dim, book, pq_len)).astype(np.float32)
    cbn = (cb ** 2).sum(-1)
    codes = rng.integers(0, book, (L, pad, pq_dim)).astype(np.uint8)
    ids = np.arange(L * pad, dtype=np.int32).reshape(L, pad)
    ids[:, -3:] = -1
    probes = rng.integers(0, L, (nq, P)).astype(np.int32)
    v, i = pk.fused_pq_topk(probes, q_rot, centers, cb, cbn, codes, ids, k,
                            pad_tile=8, interpret=True)
    # numpy ADC reference: residual LUT per (query, probe, subspace)
    nq_, P_ = probes.shape
    ref_d = np.full((nq_, P_ * pad), np.inf, np.float32)
    ref_g = np.full((nq_, P_ * pad), -1, np.int64)
    for qi in range(nq_):
        for pj in range(P_):
            sl = probes[qi, pj]
            res = (q_rot[qi] - centers[sl]).reshape(pq_dim, pq_len)
            lut = ((res[:, None, :] - cb) ** 2).sum(-1)  # [pq_dim, book]
            dist = lut[np.arange(pq_dim)[None, :],
                       codes[sl].astype(np.int64)].sum(-1)
            dist = np.where(ids[sl] < 0, np.inf, dist)
            ref_d[qi, pj * pad:(pj + 1) * pad] = dist
            ref_g[qi, pj * pad:(pj + 1) * pad] = ids[sl]
    _assert_ivf_match(v, i, ref_d, ref_g, k, atol=1e-3)


def test_fused_pq_topk_rejects_packed_codes():
    # pq_bits<8 packs several codes per byte: n_code_bytes != pq_dim
    with pytest.raises(ValueError, match="pq_bits=8"):
        pk.fused_pq_topk(np.zeros((1, 1), np.int32),
                         np.zeros((1, 8), np.float32),
                         np.zeros((2, 8), np.float32),
                         np.zeros((4, 16, 2), np.float32),
                         np.zeros((4, 16), np.float32),
                         np.zeros((2, 8, 2), np.uint8),
                         np.zeros((2, 8), np.int32), 4, interpret=True)


# -------------------------------------------------------- engine dispatch

@pytest.fixture(scope="module")
def small_db():
    rng = np.random.default_rng(3)
    db = rng.standard_normal((600, 32)).astype(np.float32)
    q = rng.standard_normal((17, 32)).astype(np.float32)
    return db, q


def test_brute_force_pallas_mode_cpu_fallback(small_db):
    # no interpret opt-in: "pallas" on CPU must fall back bit-exactly
    db, q = small_db
    bf = brute_force.build(db, metric="sqeuclidean")
    vx, ix = brute_force.search(bf, q, 10, scan_mode="xla")
    vp, ip = brute_force.search(bf, q, 10, scan_mode="pallas")
    np.testing.assert_array_equal(np.asarray(ix), np.asarray(ip))
    np.testing.assert_array_equal(np.asarray(vx), np.asarray(vp))
    with pytest.raises(ValueError, match="scan_mode"):
        brute_force.search(bf, q, 10, scan_mode="mosaic")


@pytest.mark.parametrize("metric", ["sqeuclidean", "euclidean"])
def test_brute_force_pallas_interpret_parity(small_db, monkeypatch, metric):
    monkeypatch.setenv("RAFT_TPU_PALLAS_INTERPRET", "1")
    db, q = small_db
    bf = brute_force.build(db, metric=metric)
    vx, ix = brute_force.search(bf, q, 10, scan_mode="xla")
    vp, ip = brute_force.search(bf, q, 10, scan_mode="pallas")
    np.testing.assert_allclose(np.asarray(vp), np.asarray(vx),
                               rtol=1e-4, atol=1e-4)
    assert np.mean(np.asarray(ip) == np.asarray(ix)) > 0.99


def test_ivf_flat_pallas_interpret_parity_with_overflow(monkeypatch):
    # tight pad budget forces spill: the fused path must merge the
    # XLA-scanned overflow block into the in-kernel carry's results
    rng = np.random.default_rng(5)
    db = np.concatenate([
        rng.standard_normal((500, 16)).astype(np.float32),
        rng.standard_normal((150, 16)).astype(np.float32) * 0.05 + 2.0])
    q = rng.standard_normal((9, 16)).astype(np.float32)
    idx = ivf_flat.build(db, ivf_flat.IndexParams(
        n_lists=8, list_pad_expansion=1.01))
    assert idx.overflow_data.shape[0] > 0
    vx, ix = ivf_flat.search(idx, q, 10, ivf_flat.SearchParams(
        n_probes=4, scan_mode="xla"))
    monkeypatch.setenv("RAFT_TPU_PALLAS_INTERPRET", "1")
    vp, ip = ivf_flat.search(idx, q, 10, ivf_flat.SearchParams(
        n_probes=4, scan_mode="pallas"))
    np.testing.assert_allclose(np.asarray(vp), np.asarray(vx),
                               rtol=1e-4, atol=1e-4)
    assert np.mean(np.asarray(ip) == np.asarray(ix)) > 0.99
    # and without the opt-in the same params fall back cleanly on CPU
    monkeypatch.delenv("RAFT_TPU_PALLAS_INTERPRET")
    vf, if_ = ivf_flat.search(idx, q, 10, ivf_flat.SearchParams(
        n_probes=4, scan_mode="pallas"))
    np.testing.assert_array_equal(np.asarray(if_), np.asarray(ix))


def test_ivf_flat_fused_metric_fallback(small_db, monkeypatch):
    # inner-product is outside the fused fallback matrix: "pallas" must
    # quietly use the XLA engine even with the interpret opt-in
    monkeypatch.setenv("RAFT_TPU_PALLAS_INTERPRET", "1")
    db, q = small_db
    idx = ivf_flat.build(db, ivf_flat.IndexParams(
        n_lists=8, metric="inner_product"))
    vx, ix = ivf_flat.search(idx, q, 5, ivf_flat.SearchParams(
        n_probes=4, scan_mode="xla"))
    vp, ip = ivf_flat.search(idx, q, 5, ivf_flat.SearchParams(
        n_probes=4, scan_mode="pallas"))
    np.testing.assert_array_equal(np.asarray(ip), np.asarray(ix))


def test_ivf_pq_pallas_interpret_parity(small_db, monkeypatch):
    db, q = small_db
    idx = ivf_pq.build(db, ivf_pq.IndexParams(
        n_lists=8, pq_dim=8, pq_bits=8))
    sp = dict(n_probes=4)
    vx, ix = ivf_pq.search(idx, q, 10, ivf_pq.SearchParams(
        scan_mode="cache", scan_cache_dtype=jnp.float32, **sp))
    monkeypatch.setenv("RAFT_TPU_PALLAS_INTERPRET", "1")
    vp, ip = ivf_pq.search(idx, q, 10, ivf_pq.SearchParams(
        scan_mode="pallas", scan_cache_dtype=jnp.float32, **sp))
    np.testing.assert_allclose(np.asarray(vp), np.asarray(vx),
                               rtol=1e-4, atol=1e-4)
    assert np.mean(np.asarray(ip) == np.asarray(ix)) > 0.99
    monkeypatch.delenv("RAFT_TPU_PALLAS_INTERPRET")
    vf, if_ = ivf_pq.search(idx, q, 10, ivf_pq.SearchParams(
        scan_mode="pallas", scan_cache_dtype=jnp.float32, **sp))
    np.testing.assert_array_equal(np.asarray(if_), np.asarray(ix))


def test_fused_dispatch_cpu_defaults():
    # without the interpret hook, CPU never routes to the fused kernels
    assert pk.fused_dispatch("brute_force", "xla") == (False, False)
    assert pk.fused_dispatch("brute_force", "pallas") == (False, False)
    assert pk.fused_dispatch("brute_force", "auto") == (False, False)


def test_fused_crossover_reads_probe_verdicts():
    key = pk.fused_platform_key()
    try:
        pk.set_fused_crossover(key, {"brute_force": True, "ivf_pq": False})
        assert pk.fused_crossover("brute_force") is True
        assert pk.fused_crossover("ivf_pq") is False
        assert pk.fused_crossover("ivf_flat") is False  # unmeasured
    finally:
        pk.set_fused_crossover(key, None)
    assert pk.fused_crossover("brute_force") is False  # conservative


# --------------------------------------------- TOPK_PAD exemption (no 2x pad)

def test_select_k_pad_rules_flag_controls_k_padding():
    import importlib

    import jax

    # the package re-exports the select_k FUNCTION under the same name;
    # the module itself holds the pad-rule hooks
    sk = importlib.import_module("raft_tpu.ops.select_k")

    key = sk._platform_key()
    try:
        sk.set_pad_rules(key, [{"n": 256, "k": 10, "k_pad": 64}])
        v = jnp.zeros((4, 256), jnp.float32)
        padded = str(jax.make_jaxpr(
            lambda x: sk.select_k(x, 10, algo=sk.SelectAlgo.DIRECT))(v))
        exempt = str(jax.make_jaxpr(
            lambda x: sk.select_k(x, 10, algo=sk.SelectAlgo.DIRECT,
                                  pad_rules=False))(v))
        assert "k=64" in padded      # the measured pad rule applies...
        assert "k=64" not in exempt  # ...but never on the exempt path
        assert "k=10" in exempt
    finally:
        sk.set_pad_rules(key, None)


def test_fused_ivf_dispatch_merge_is_pad_exempt(monkeypatch):
    """The fused path's only select_k calls are the XLA coarse probe
    selection (a real slab — pad rules apply) and the overflow merge over
    the in-kernel carry (already selected — MUST be pad-exempt)."""
    rng = np.random.default_rng(7)
    db = np.concatenate([
        rng.standard_normal((400, 16)).astype(np.float32),
        rng.standard_normal((120, 16)).astype(np.float32) * 0.05 + 2.0])
    q = rng.standard_normal((5, 16)).astype(np.float32)
    idx = ivf_flat.build(db, ivf_flat.IndexParams(
        n_lists=8, list_pad_expansion=1.01))
    assert idx.overflow_data.shape[0] > 0

    calls = []
    real = ivf_flat.select_k

    def spy(values, k, *a, **kw):
        calls.append(kw.get("pad_rules", True))
        return real(values, k, *a, **kw)

    monkeypatch.setattr(ivf_flat, "select_k", spy)
    monkeypatch.setenv("RAFT_TPU_PALLAS_INTERPRET", "1")
    ivf_flat.search(idx, q, 10, ivf_flat.SearchParams(
        n_probes=4, scan_mode="pallas"))
    assert calls, "fused dispatch traced no select_k call"
    assert calls.count(False) >= 1, (
        "overflow merge over the in-kernel carry must pass pad_rules=False"
    )


# --------------------------------------- fused cagra beam search (VMEM beam)

def _np_beam_walk(q, db, graph, seeds, k, itopk, width, max_iter):
    """Greedy beam walk, one query, squared L2 — the kernel's documented
    semantics in plain numpy: first-occurrence seed/target dedup, ``width``
    cheapest-unexpanded parents per hop, stable ascending merges, fixed
    iteration budget. float64 scoring so the reference's tie/order
    decisions never depend on fp32 rounding."""
    def d2(ids):
        diff = db[ids].astype(np.float64) - q.astype(np.float64)
        return (diff * diff).sum(-1)

    seen = []
    for s in seeds:
        s = int(s)
        if s >= 0 and s not in seen:
            seen.append(s)
    buf_ids = np.array(seen, np.int64)
    buf_d = d2(buf_ids)
    order = np.argsort(buf_d, kind="stable")[:itopk]
    buf_ids, buf_d = buf_ids[order], buf_d[order]
    flags = np.zeros(len(buf_ids), bool)
    for _ in range(max_iter):
        unexp = np.nonzero(~flags)[0]
        if unexp.size == 0:
            break
        parents = unexp[:width]
        flags[parents] = True
        targets = []
        for p in parents:
            for t in graph[buf_ids[p]]:
                t = int(t)
                if t >= 0 and t not in targets and t not in buf_ids:
                    targets.append(t)
        if not targets:
            continue
        t_ids = np.array(targets, np.int64)
        all_ids = np.concatenate([buf_ids, t_ids])
        all_d = np.concatenate([buf_d, d2(t_ids)])
        all_f = np.concatenate([flags, np.zeros(len(t_ids), bool)])
        order = np.argsort(all_d, kind="stable")[:itopk]
        buf_ids, buf_d, flags = all_ids[order], all_d[order], all_f[order]
    out_d = np.full(k, np.inf)
    out_i = np.full(k, -1, np.int64)
    m = min(k, len(buf_ids))
    out_d[:m], out_i[:m] = buf_d[:m], buf_ids[:m]
    return out_d, out_i


# (seed, n, dim, degree, nq, k, itopk, width, n_seeds, ct) — spans the
# tile boundaries: width*degree below/at/above one ct chunk, a ragged
# last graph tile (wd=12 padded to 16), and multi-chunk seed streams.
_CAGRA_COMBOS = [
    (0, 500, 24, 8, 4, 5, 16, 1, 20, 16),
    (2, 300, 24, 6, 3, 4, 16, 2, 20, 16),   # ragged: wd=12 < chunk 16
    (1, 600, 32, 16, 2, 8, 64, 4, 64, 32),  # wd=64: two chunks per hop
]


def _cagra_case(seed, n, dim, degree, nq, n_seeds):
    rng = np.random.default_rng(seed)
    data = rng.standard_normal((n, dim)).astype(np.float32)
    q = rng.standard_normal((nq, dim)).astype(np.float32)
    graph = rng.integers(0, n, (n, degree)).astype(np.int32)
    graph[5, :3] = -1  # invalid edges must be skipped, not scored
    seeds = rng.integers(0, n, (nq, n_seeds)).astype(np.int32)
    seeds[:, 1] = seeds[:, 0]  # duplicate seed ids dedup to one entry
    return data, q, graph, seeds


@pytest.mark.parametrize(
    "seed,n,dim,degree,nq,k,itopk,width,n_seeds,ct", _CAGRA_COMBOS)
def test_fused_cagra_matches_numpy_beam_walk(seed, n, dim, degree, nq, k,
                                             itopk, width, n_seeds, ct):
    data, q, graph, seeds = _cagra_case(seed, n, dim, degree, nq, n_seeds)
    fd, fi = pk.fused_cagra_topk(q, data, graph, seeds, k, itopk, width,
                                 max_iter=12, ct=ct, interpret=True)
    fd, fi = np.asarray(fd), np.asarray(fi)
    for r in range(nq):
        rd, ri = _np_beam_walk(q[r], data, graph, seeds[r], k, itopk,
                               width, 12)
        np.testing.assert_array_equal(fi[r], ri)
        finite = np.isfinite(rd)
        np.testing.assert_allclose(fd[r][finite], rd[finite],
                                   rtol=1e-5, atol=1e-5)
        assert np.all(fd[r][~finite] == np.inf)


@pytest.mark.parametrize(
    "seed,n,dim,degree,nq,k,itopk,width,n_seeds,ct",
    [_CAGRA_COMBOS[0], _CAGRA_COMBOS[2]])
def test_fused_cagra_bit_parity_vs_xla_core(seed, n, dim, degree, nq, k,
                                            itopk, width, n_seeds, ct):
    """Interpret-mode fused core vs ``_search_jit``, BITWISE — same
    dot-accumulate order, same stable merge order, same done-freeze exit.
    Pinned at fixed seeds on combos where XLA:CPU's gemv blocking agrees
    with the kernel's whole-chunk dot (other shapes drift 1 ULP in XLA's
    fused einsum, not in the kernel — see the numpy-reference test)."""
    from raft_tpu.neighbors import cagra
    from raft_tpu.ops.distance import DistanceType

    data, q, graph, seeds = _cagra_case(seed, n, dim, degree, nq, n_seeds)
    fw = jnp.zeros((1,), jnp.uint32)
    xd, xi = cagra.search_core(
        q, data, data, jnp.asarray(graph), jnp.asarray(seeds), fw,
        DistanceType.L2Expanded, k, itopk, width, 12, False, False)
    fd, fi = pk.fused_cagra_topk(q, data, graph, seeds, k, itopk, width,
                                 max_iter=12, ct=ct, interpret=True)
    np.testing.assert_array_equal(np.asarray(fd), np.asarray(xd))
    np.testing.assert_array_equal(np.asarray(fi), np.asarray(xi))


def test_plan_fused_cagra_tile_budget_and_alignment():
    for budget in (256 << 10, 1 << 20, 4 << 20, 16 << 20):
        ct = pk.plan_fused_cagra_tile(64, 4, 32, 128, 128,
                                      vmem_budget=budget)
        assert ct >= 8 and ct % 8 == 0
        assert pk.fused_cagra_vmem_bytes(ct, 128, 64, 4, 32, 128) <= budget
    # monotone non-decreasing in budget
    cts = [pk.plan_fused_cagra_tile(64, 4, 32, 128, 128, vmem_budget=b)
           for b in (256 << 10, 1 << 20, 16 << 20)]
    assert cts == sorted(cts)


def test_plan_fused_cagra_tile_caps_at_widest_stream():
    # the widest stream the walk scores is max(width*degree, n_seeds):
    # a bigger scratch would sit empty, so the plan must not exceed its
    # 8-aligned round-up even under a huge budget
    ct = pk.plan_fused_cagra_tile(64, 1, 8, 32, 12, vmem_budget=1 << 30)
    assert ct == 16  # round_up(max(8, 12, 8), 8)
    assert pk.plan_fused_cagra_tile(
        64, 4, 64, 32, 8, vmem_budget=1 << 30) == 256


def test_fused_cagra_workspace_excludes_any_space_operands():
    # dataset/graph are ANY-space ARGUMENTS, not staged temps: workspace
    # must not scale with n (the design point of the fused walk)
    small = pk.fused_cagra_workspace_bytes(64, 10_000, 128, 32, 64, 1,
                                           64, 10)
    large = pk.fused_cagra_workspace_bytes(64, 10_000_000, 128, 32, 64, 1,
                                           64, 10)
    assert small == large > 0


def test_fused_cagra_rejects_large_itopk(rng):
    data, q, graph, seeds = _cagra_case(0, 300, 16, 8, 2, 16)
    with pytest.raises(ValueError, match="itopk"):
        pk.fused_cagra_topk(q, data, graph, seeds, 10, itopk=2048)


def test_cagra_dispatch_fallback_matrix(monkeypatch):
    """scan_mode="pallas" + interpret opt-in routes the fused engine only
    inside the eligibility envelope; everything else must fall back to
    XLA with the matrix's closed reason (docs/tuning.md)."""
    from raft_tpu.core.bitset import Bitset
    from raft_tpu.neighbors import cagra

    monkeypatch.setenv("RAFT_TPU_PALLAS_INTERPRET", "1")
    rng = np.random.default_rng(11)
    n, dim = 400, 16
    data = rng.standard_normal((n, dim)).astype(np.float32)
    q = rng.standard_normal((3, dim)).astype(np.float32)
    graph = jnp.asarray(rng.integers(0, n, (n, 8)).astype(np.int32))
    idx = cagra.Index(cagra.IndexParams(graph_degree=8),
                      jnp.asarray(data), graph)
    pal = dict(itopk_size=16, scan_mode="pallas")

    _, _, rec = cagra.search(idx, q, 5, cagra.SearchParams(**pal),
                             explain=True)
    assert (rec.engine, rec.reason) == ("pallas", "interpret")

    ip = cagra.Index(
        cagra.IndexParams(graph_degree=8,
                          metric=cagra.DistanceType.InnerProduct),
        jnp.asarray(data), graph)
    _, _, rec = cagra.search(ip, q, 5, cagra.SearchParams(**pal),
                             explain=True)
    assert (rec.engine, rec.reason) == ("xla", "non_l2")

    flt = Bitset.create(n)
    _, _, rec = cagra.search(idx, q, 5, cagra.SearchParams(**pal),
                             filter=flt, explain=True)
    assert (rec.engine, rec.reason) == ("xla", "filtered")

    # itopk beyond the kernel's 1024 buffer cap (dataset must be larger
    # than itopk or the XLA fallback's own seed top-k can't run either)
    big_n = 1200
    big = cagra.Index(
        cagra.IndexParams(graph_degree=8),
        jnp.asarray(rng.standard_normal((big_n, dim)).astype(np.float32)),
        jnp.asarray(rng.integers(0, big_n, (big_n, 8)).astype(np.int32)))
    _, _, rec = cagra.search(
        big, q, 5, cagra.SearchParams(itopk_size=1056, scan_mode="pallas"),
        explain=True)
    assert (rec.engine, rec.reason) == ("xla", "k_gt_1024")

    _, _, rec = cagra.search(
        idx, q, 5, cagra.SearchParams(itopk_size=16, scan_dtype="bfloat16",
                                      scan_mode="pallas"), explain=True)
    assert (rec.engine, rec.reason) == ("xla", "fast_scan")

    # TPU absent, no interpret opt-in: auto stays on XLA
    monkeypatch.delenv("RAFT_TPU_PALLAS_INTERPRET")
    _, _, rec = cagra.search(idx, q, 5,
                             cagra.SearchParams(itopk_size=16),
                             explain=True)
    assert (rec.engine, rec.reason) == ("xla", "tpu_absent")


def test_cagra_public_api_interpret_bit_parity(monkeypatch):
    # the whole public path — seed lattice, padding, epilogue — must be
    # bit-identical between engines when the fused core runs interpret
    from raft_tpu.neighbors import cagra

    monkeypatch.setenv("RAFT_TPU_PALLAS_INTERPRET", "1")
    rng = np.random.default_rng(3)
    n, dim = 800, 32
    data = rng.standard_normal((n, dim)).astype(np.float32)
    q = rng.standard_normal((5, dim)).astype(np.float32)
    idx = cagra.Index(cagra.IndexParams(graph_degree=8), jnp.asarray(data),
                      jnp.asarray(rng.integers(0, n, (n, 8)).astype(
                          np.int32)))
    for metric in (cagra.DistanceType.L2Expanded,
                   cagra.DistanceType.L2SqrtExpanded):
        mi = cagra.Index(cagra.IndexParams(graph_degree=8, metric=metric),
                         idx.dataset, idx.graph)
        vx, ix = cagra.search(mi, q, 5, cagra.SearchParams(
            itopk_size=32, search_width=2, scan_mode="xla"))
        vp, ip = cagra.search(mi, q, 5, cagra.SearchParams(
            itopk_size=32, search_width=2, scan_mode="pallas"))
        np.testing.assert_array_equal(np.asarray(vx), np.asarray(vp))
        np.testing.assert_array_equal(np.asarray(ix), np.asarray(ip))


def test_cagra_fused_recall_floor(monkeypatch):
    """Recall ≥0.95 through the fused engine on a real built graph — the
    walk must actually navigate, not just agree with itself."""
    from raft_tpu.neighbors import brute_force as bf
    from raft_tpu.neighbors import cagra
    from raft_tpu.stats import neighborhood_recall

    monkeypatch.setenv("RAFT_TPU_PALLAS_INTERPRET", "1")
    rng = np.random.default_rng(7)
    db = rng.standard_normal((3000, 32)).astype(np.float32)
    q = rng.standard_normal((32, 32)).astype(np.float32)
    _, gt = bf.knn(q, db, k=10, metric="sqeuclidean")
    idx = cagra.build(db, cagra.IndexParams(
        intermediate_graph_degree=48, graph_degree=24,
        build_algo=cagra.BuildAlgo.NN_DESCENT, nn_descent_niter=12))
    _, i = cagra.search(idx, q, 10, cagra.SearchParams(
        itopk_size=64, search_width=2, scan_mode="pallas"))
    recall = float(neighborhood_recall(np.asarray(i), np.asarray(gt)))
    assert recall >= 0.95, f"fused recall {recall}"


# ------------------------------------------------------------- heavy shapes

@pytest.mark.slow
def test_fused_l2_topk_heavy_parity(rng):
    x = rng.standard_normal((128, 64)).astype(np.float32)
    y = rng.standard_normal((5000, 64)).astype(np.float32)
    v, i = pk.fused_l2_topk(x, y, 100, tm=64, tn=512, interpret=True)
    d = ((x[:, None, :] - y[None, :, :]) ** 2).sum(-1)
    _assert_topk_match(v, i, d, 100, atol=1e-3)


@pytest.mark.slow
def test_fused_ivf_topk_heavy_parity(rng):
    L, pad, rot, nq, P, k = 16, 128, 64, 32, 8, 64
    data = rng.standard_normal((L, pad, rot)).astype(np.float32)
    ids = np.arange(L * pad, dtype=np.int32).reshape(L, pad)
    norms = (data ** 2).sum(-1)
    probes = rng.integers(0, L, (nq, P)).astype(np.int32)
    qres = rng.standard_normal((nq, P, rot)).astype(np.float32)
    qn = (qres ** 2).sum(-1)
    v, i = pk.fused_ivf_topk(probes, qres, qn, data, norms, ids, k,
                             pad_tile=32, clamp=True, interpret=True)
    ref_d, ref_gid = _ivf_ref(probes, qres, data, norms, ids, clamp=True)
    _assert_ivf_match(v, i, ref_d, ref_gid, k, atol=1e-3)
