"""Fused Pallas scan+select (``scan_mode="pallas"``) — interpret-mode
parity, VMEM planner properties, and engine dispatch.

Every kernel test forces TINY tiles so the running top-k carry crosses
the merge boundary (several inner grid steps revisit the output block)
and uses ragged extents so the padded tails exercise the +inf/-1
sentinel path. References are plain numpy. Dispatch tests drive the
public search APIs: on CPU ``scan_mode="pallas"`` must silently fall
back to XLA; with RAFT_TPU_PALLAS_INTERPRET=1 it must route through the
Mosaic interpreter and epsilon-match the XLA engines end to end.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from raft_tpu.neighbors import brute_force, ivf_flat, ivf_pq
from raft_tpu.ops import pallas_kernels as pk


@pytest.fixture(scope="module", autouse=True)
def _drop_interpret_executables():
    """Interpret-mode pallas_call lowers to very large XLA:CPU programs;
    keeping their executables cached for the rest of the session pushes
    the LLVM JIT into its known environment-level segfault a few hundred
    tests later. Drop them (and everything else — later modules recompile
    their own shapes anyway) when this module is done."""
    yield
    jax.clear_caches()


@pytest.fixture()
def rng():
    return np.random.default_rng(0)


def _np_topk(d, k):
    """Ascending (values, ids) per row; +inf / -1 past the row's extent."""
    m, n = d.shape
    order = np.argsort(d, axis=1, kind="stable")[:, :k]
    vals = np.take_along_axis(d, order, axis=1)
    if k > n:
        pad = np.full((m, k - n), np.inf, d.dtype)
        vals = np.concatenate([vals, pad], axis=1)
        order = np.concatenate(
            [order, np.full((m, k - n), -1, order.dtype)], axis=1)
    return vals, order


def _assert_topk_match(v, i, ref_d, k, atol=1e-4):
    """Sorted-value parity + id consistency (ties at the k boundary may
    reorder ids between engines, so id equality is checked through the
    distance each id maps back to, not positionally)."""
    v = np.asarray(v)
    i = np.asarray(i)
    ref_v, _ = _np_topk(ref_d, k)
    np.testing.assert_allclose(v, ref_v, rtol=1e-4, atol=atol)
    valid = i >= 0
    rows, cols = np.nonzero(valid)
    picked = ref_d[rows, i[rows, cols]]
    np.testing.assert_allclose(v[valid], picked, rtol=1e-4, atol=atol)
    assert np.all(v[~valid] == np.inf)


# ------------------------------------------------------------ VMEM planner

def test_solve_vmem_tiles_respects_budget():
    from raft_tpu.core.resources import solve_vmem_tiles

    budget = 12 << 20
    for cell, ob, ib, imax in [(12, 600, 516, 1024), (4, 4096, 8, 131072),
                               (12, 33000, 516, 256)]:
        outer, inner = solve_vmem_tiles(budget, cell, ob, ib, imax)
        assert outer % 8 == 0 and inner % 128 == 0
        if (outer, inner) != (8, 128):  # degraded floor is best-effort
            assert outer * ob + inner * ib + outer * inner * cell <= budget


@pytest.mark.parametrize("m,n,dim,k", [
    (10_000, 1_000_000, 128, 100), (100, 300, 16, 10), (8, 128, 8, 1)])
def test_plan_fused_topk_tiles_fit_vmem(m, n, dim, k):
    tm, tn = pk.plan_fused_topk_tiles(m, n, dim, k)
    assert tm % 8 == 0 and tn % 128 == 0
    assert pk.fused_topk_tile_bytes(tm, tn, dim, k) <= pk.DEFAULT_VMEM_BUDGET
    assert pk.fused_topk_tile_bytes(tm, tn, dim, k) <= pk.VMEM_LIMIT_BYTES


@pytest.mark.parametrize("list_pad", [7, 24, 1000, 1464])
def test_plan_fused_ivf_tile_divides_layout(list_pad):
    for itemsize in (2, 4):
        pt = pk.plan_fused_ivf_tile(list_pad, 128, 100, itemsize)
        assert list_pad % pt == 0
        assert (pk.fused_ivf_vmem_bytes(pt, 128, 100, itemsize)
                <= pk.DEFAULT_VMEM_BUDGET or pt == 1)
    # the sift-1M slab fits whole: one DMA per probe, no inner axis
    assert pk.plan_fused_ivf_tile(1464, 128, 100, 4) == 1464


@pytest.mark.parametrize("list_pad", [16, 24, 1464])
def test_plan_fused_pq_tile_divides_layout(list_pad):
    pt = pk.plan_fused_pq_tile(list_pad, 64, 256, 2, 100)
    assert list_pad % pt == 0
    assert (pk.fused_pq_vmem_bytes(pt, 64, 256, 2, 100)
            <= pk.DEFAULT_VMEM_BUDGET or pt == 1)


def test_fused_workspace_accounting_positive():
    assert pk.fused_topk_workspace_bytes(100, 1000, 32, 10) > 0
    assert pk.fused_ivf_workspace_bytes(16, 4, 32, 8, 24, 10) > 0
    assert pk.fused_pq_workspace_bytes(16, 4, 32, 8, 24, 8, 256, 4, 10) > 0


# --------------------------------------------- fused_l2_topk (brute force)

@pytest.mark.parametrize("k", [1, 10, 64])
def test_fused_l2_topk_parity(rng, k):
    # tn=128 over n=300 → three db tiles: the carry merges twice
    x = rng.standard_normal((23, 16)).astype(np.float32)
    y = rng.standard_normal((300, 16)).astype(np.float32)
    v, i = pk.fused_l2_topk(x, y, k, tm=8, tn=128, interpret=True)
    d = ((x[:, None, :] - y[None, :, :]) ** 2).sum(-1)
    _assert_topk_match(v, i, d, k)


def test_fused_l2_topk_k_exceeds_rows(rng):
    # k > n: the tail of the carry stays at the +inf / -1 sentinels
    x = rng.standard_normal((9, 8)).astype(np.float32)
    y = rng.standard_normal((20, 8)).astype(np.float32)
    v, i = pk.fused_l2_topk(x, y, 64, tm=8, tn=128, interpret=True)
    d = ((x[:, None, :] - y[None, :, :]) ** 2).sum(-1)
    _assert_topk_match(v, i, d, 64)
    assert np.all(np.asarray(i)[:, 20:] == -1)


def test_fused_l2_topk_rejects_large_k(rng):
    with pytest.raises(ValueError, match="small-k"):
        pk.fused_l2_topk(np.zeros((8, 8), np.float32),
                         np.zeros((8, 8), np.float32), 2000)


# ------------------------------------------------ fused_ivf_topk (flat/pq)

def _ivf_ref(probes, qres, list_data, row_norms, ids, clamp):
    """Per-query candidate distances over probed slabs, -1 slots → +inf."""
    nq, P = probes.shape
    pad = list_data.shape[1]
    d = np.full((nq, P * pad), np.inf, np.float32)
    gid = np.full((nq, P * pad), -1, np.int64)
    for qi in range(nq):
        for pj in range(P):
            sl = probes[qi, pj]
            qn = (qres[qi, pj].astype(np.float32) ** 2).sum()
            dots = list_data[sl].astype(np.float32) @ qres[qi, pj]
            dist = qn + row_norms[sl] - 2.0 * dots
            if clamp:
                dist = np.maximum(dist, 0.0)
            dist = np.where(ids[sl] < 0, np.inf, dist)
            d[qi, pj * pad:(pj + 1) * pad] = dist
            gid[qi, pj * pad:(pj + 1) * pad] = ids[sl]
    return d, gid


def _assert_ivf_match(v, i, ref_d, ref_gid, k, atol=1e-4):
    v, i = np.asarray(v), np.asarray(i)
    order = np.argsort(ref_d, axis=1, kind="stable")[:, :k]
    ref_v = np.take_along_axis(ref_d, order, axis=1)
    np.testing.assert_allclose(np.where(v == np.inf, np.inf, v), ref_v,
                               rtol=1e-4, atol=atol)
    # ids map back to a distance the candidate set actually holds for
    # them (a slab probed twice contributes the same id at DIFFERENT
    # residual distances — any of its copies is a valid pairing)
    for qi in range(v.shape[0]):
        lut = {}
        for dist, g in zip(ref_d[qi], ref_gid[qi]):
            if g >= 0:
                lut.setdefault(g, []).append(dist)
        for dist, g in zip(v[qi], i[qi]):
            if g < 0:
                assert dist == np.inf
            else:
                assert any(abs(c - dist) <= atol + 1e-4 * abs(dist)
                           for c in lut[g])


@pytest.mark.parametrize("k", [1, 10])
def test_fused_ivf_topk_parity_carry_boundary(rng, k):
    # pad_tile=8 over list_pad=24 → three slab tiles per probe
    L, pad, rot, nq, P = 6, 24, 16, 5, 3
    data = rng.standard_normal((L, pad, rot)).astype(np.float32)
    ids = np.arange(L * pad, dtype=np.int32).reshape(L, pad)
    ids[:, -5:] = -1  # ragged tails: unfilled slots
    norms = (data.astype(np.float32) ** 2).sum(-1)
    probes = rng.integers(0, L, (nq, P)).astype(np.int32)
    qres = rng.standard_normal((nq, P, rot)).astype(np.float32)
    qn = (qres ** 2).sum(-1)
    v, i = pk.fused_ivf_topk(probes, qres, qn, data, norms, ids, k,
                             pad_tile=8, clamp=True, interpret=True)
    ref_d, ref_gid = _ivf_ref(probes, qres, data, norms, ids, clamp=True)
    _assert_ivf_match(v, i, ref_d, ref_gid, k)


def test_fused_ivf_topk_bf16_cache_fp32_accum(rng):
    # bf16 slab upcast in-kernel, fp32 accumulation (the pq scan cache)
    L, pad, rot, nq, P, k = 4, 16, 8, 4, 2, 6
    data32 = rng.standard_normal((L, pad, rot)).astype(np.float32)
    data = data32.astype(jnp.bfloat16)
    ids = np.arange(L * pad, dtype=np.int32).reshape(L, pad)
    norms = (np.asarray(data, np.float32) ** 2).sum(-1)
    probes = rng.integers(0, L, (nq, P)).astype(np.int32)
    qres = rng.standard_normal((nq, P, rot)).astype(np.float32)
    qn = (qres ** 2).sum(-1)
    v, i = pk.fused_ivf_topk(probes, qres, qn, data, norms, ids, k,
                             pad_tile=8, clamp=False, interpret=True)
    ref_d, ref_gid = _ivf_ref(probes, np.asarray(qres),
                              np.asarray(data, np.float32), norms, ids,
                              clamp=False)
    _assert_ivf_match(v, i, ref_d, ref_gid, k, atol=5e-2)


def test_fused_ivf_topk_rejects_non_divisor_tile(rng):
    L, pad, rot = 2, 24, 8
    data = np.zeros((L, pad, rot), np.float32)
    with pytest.raises(ValueError, match="does not divide"):
        pk.fused_ivf_topk(np.zeros((1, 1), np.int32),
                          np.zeros((1, 1, rot), np.float32),
                          np.zeros((1, 1), np.float32), data,
                          np.zeros((L, pad), np.float32),
                          np.zeros((L, pad), np.int32), 4, pad_tile=7,
                          interpret=True)


# ------------------------------------------------- fused_pq_topk (lut)

def test_fused_pq_topk_parity(rng):
    L, pad, pq_dim, book, pq_len, nq, P, k = 4, 16, 4, 16, 2, 3, 2, 5
    rot = pq_dim * pq_len
    centers = rng.standard_normal((L, rot)).astype(np.float32)
    q_rot = rng.standard_normal((nq, rot)).astype(np.float32)
    cb = rng.standard_normal((pq_dim, book, pq_len)).astype(np.float32)
    cbn = (cb ** 2).sum(-1)
    codes = rng.integers(0, book, (L, pad, pq_dim)).astype(np.uint8)
    ids = np.arange(L * pad, dtype=np.int32).reshape(L, pad)
    ids[:, -3:] = -1
    probes = rng.integers(0, L, (nq, P)).astype(np.int32)
    v, i = pk.fused_pq_topk(probes, q_rot, centers, cb, cbn, codes, ids, k,
                            pad_tile=8, interpret=True)
    # numpy ADC reference: residual LUT per (query, probe, subspace)
    nq_, P_ = probes.shape
    ref_d = np.full((nq_, P_ * pad), np.inf, np.float32)
    ref_g = np.full((nq_, P_ * pad), -1, np.int64)
    for qi in range(nq_):
        for pj in range(P_):
            sl = probes[qi, pj]
            res = (q_rot[qi] - centers[sl]).reshape(pq_dim, pq_len)
            lut = ((res[:, None, :] - cb) ** 2).sum(-1)  # [pq_dim, book]
            dist = lut[np.arange(pq_dim)[None, :],
                       codes[sl].astype(np.int64)].sum(-1)
            dist = np.where(ids[sl] < 0, np.inf, dist)
            ref_d[qi, pj * pad:(pj + 1) * pad] = dist
            ref_g[qi, pj * pad:(pj + 1) * pad] = ids[sl]
    _assert_ivf_match(v, i, ref_d, ref_g, k, atol=1e-3)


def test_fused_pq_topk_rejects_packed_codes():
    # pq_bits<8 packs several codes per byte: n_code_bytes != pq_dim
    with pytest.raises(ValueError, match="pq_bits=8"):
        pk.fused_pq_topk(np.zeros((1, 1), np.int32),
                         np.zeros((1, 8), np.float32),
                         np.zeros((2, 8), np.float32),
                         np.zeros((4, 16, 2), np.float32),
                         np.zeros((4, 16), np.float32),
                         np.zeros((2, 8, 2), np.uint8),
                         np.zeros((2, 8), np.int32), 4, interpret=True)


# -------------------------------------------------------- engine dispatch

@pytest.fixture(scope="module")
def small_db():
    rng = np.random.default_rng(3)
    db = rng.standard_normal((600, 32)).astype(np.float32)
    q = rng.standard_normal((17, 32)).astype(np.float32)
    return db, q


def test_brute_force_pallas_mode_cpu_fallback(small_db):
    # no interpret opt-in: "pallas" on CPU must fall back bit-exactly
    db, q = small_db
    bf = brute_force.build(db, metric="sqeuclidean")
    vx, ix = brute_force.search(bf, q, 10, scan_mode="xla")
    vp, ip = brute_force.search(bf, q, 10, scan_mode="pallas")
    np.testing.assert_array_equal(np.asarray(ix), np.asarray(ip))
    np.testing.assert_array_equal(np.asarray(vx), np.asarray(vp))
    with pytest.raises(ValueError, match="scan_mode"):
        brute_force.search(bf, q, 10, scan_mode="mosaic")


@pytest.mark.parametrize("metric", ["sqeuclidean", "euclidean"])
def test_brute_force_pallas_interpret_parity(small_db, monkeypatch, metric):
    monkeypatch.setenv("RAFT_TPU_PALLAS_INTERPRET", "1")
    db, q = small_db
    bf = brute_force.build(db, metric=metric)
    vx, ix = brute_force.search(bf, q, 10, scan_mode="xla")
    vp, ip = brute_force.search(bf, q, 10, scan_mode="pallas")
    np.testing.assert_allclose(np.asarray(vp), np.asarray(vx),
                               rtol=1e-4, atol=1e-4)
    assert np.mean(np.asarray(ip) == np.asarray(ix)) > 0.99


def test_ivf_flat_pallas_interpret_parity_with_overflow(monkeypatch):
    # tight pad budget forces spill: the fused path must merge the
    # XLA-scanned overflow block into the in-kernel carry's results
    rng = np.random.default_rng(5)
    db = np.concatenate([
        rng.standard_normal((500, 16)).astype(np.float32),
        rng.standard_normal((150, 16)).astype(np.float32) * 0.05 + 2.0])
    q = rng.standard_normal((9, 16)).astype(np.float32)
    idx = ivf_flat.build(db, ivf_flat.IndexParams(
        n_lists=8, list_pad_expansion=1.01))
    assert idx.overflow_data.shape[0] > 0
    vx, ix = ivf_flat.search(idx, q, 10, ivf_flat.SearchParams(
        n_probes=4, scan_mode="xla"))
    monkeypatch.setenv("RAFT_TPU_PALLAS_INTERPRET", "1")
    vp, ip = ivf_flat.search(idx, q, 10, ivf_flat.SearchParams(
        n_probes=4, scan_mode="pallas"))
    np.testing.assert_allclose(np.asarray(vp), np.asarray(vx),
                               rtol=1e-4, atol=1e-4)
    assert np.mean(np.asarray(ip) == np.asarray(ix)) > 0.99
    # and without the opt-in the same params fall back cleanly on CPU
    monkeypatch.delenv("RAFT_TPU_PALLAS_INTERPRET")
    vf, if_ = ivf_flat.search(idx, q, 10, ivf_flat.SearchParams(
        n_probes=4, scan_mode="pallas"))
    np.testing.assert_array_equal(np.asarray(if_), np.asarray(ix))


def test_ivf_flat_fused_metric_fallback(small_db, monkeypatch):
    # inner-product is outside the fused fallback matrix: "pallas" must
    # quietly use the XLA engine even with the interpret opt-in
    monkeypatch.setenv("RAFT_TPU_PALLAS_INTERPRET", "1")
    db, q = small_db
    idx = ivf_flat.build(db, ivf_flat.IndexParams(
        n_lists=8, metric="inner_product"))
    vx, ix = ivf_flat.search(idx, q, 5, ivf_flat.SearchParams(
        n_probes=4, scan_mode="xla"))
    vp, ip = ivf_flat.search(idx, q, 5, ivf_flat.SearchParams(
        n_probes=4, scan_mode="pallas"))
    np.testing.assert_array_equal(np.asarray(ip), np.asarray(ix))


def test_ivf_pq_pallas_interpret_parity(small_db, monkeypatch):
    db, q = small_db
    idx = ivf_pq.build(db, ivf_pq.IndexParams(
        n_lists=8, pq_dim=8, pq_bits=8))
    sp = dict(n_probes=4)
    vx, ix = ivf_pq.search(idx, q, 10, ivf_pq.SearchParams(
        scan_mode="cache", scan_cache_dtype=jnp.float32, **sp))
    monkeypatch.setenv("RAFT_TPU_PALLAS_INTERPRET", "1")
    vp, ip = ivf_pq.search(idx, q, 10, ivf_pq.SearchParams(
        scan_mode="pallas", scan_cache_dtype=jnp.float32, **sp))
    np.testing.assert_allclose(np.asarray(vp), np.asarray(vx),
                               rtol=1e-4, atol=1e-4)
    assert np.mean(np.asarray(ip) == np.asarray(ix)) > 0.99
    monkeypatch.delenv("RAFT_TPU_PALLAS_INTERPRET")
    vf, if_ = ivf_pq.search(idx, q, 10, ivf_pq.SearchParams(
        scan_mode="pallas", scan_cache_dtype=jnp.float32, **sp))
    np.testing.assert_array_equal(np.asarray(if_), np.asarray(ix))


def test_fused_dispatch_cpu_defaults():
    # without the interpret hook, CPU never routes to the fused kernels
    assert pk.fused_dispatch("brute_force", "xla") == (False, False)
    assert pk.fused_dispatch("brute_force", "pallas") == (False, False)
    assert pk.fused_dispatch("brute_force", "auto") == (False, False)


def test_fused_crossover_reads_probe_verdicts():
    key = pk.fused_platform_key()
    try:
        pk.set_fused_crossover(key, {"brute_force": True, "ivf_pq": False})
        assert pk.fused_crossover("brute_force") is True
        assert pk.fused_crossover("ivf_pq") is False
        assert pk.fused_crossover("ivf_flat") is False  # unmeasured
    finally:
        pk.set_fused_crossover(key, None)
    assert pk.fused_crossover("brute_force") is False  # conservative


# --------------------------------------------- TOPK_PAD exemption (no 2x pad)

def test_select_k_pad_rules_flag_controls_k_padding():
    import importlib

    import jax

    # the package re-exports the select_k FUNCTION under the same name;
    # the module itself holds the pad-rule hooks
    sk = importlib.import_module("raft_tpu.ops.select_k")

    key = sk._platform_key()
    try:
        sk.set_pad_rules(key, [{"n": 256, "k": 10, "k_pad": 64}])
        v = jnp.zeros((4, 256), jnp.float32)
        padded = str(jax.make_jaxpr(
            lambda x: sk.select_k(x, 10, algo=sk.SelectAlgo.DIRECT))(v))
        exempt = str(jax.make_jaxpr(
            lambda x: sk.select_k(x, 10, algo=sk.SelectAlgo.DIRECT,
                                  pad_rules=False))(v))
        assert "k=64" in padded      # the measured pad rule applies...
        assert "k=64" not in exempt  # ...but never on the exempt path
        assert "k=10" in exempt
    finally:
        sk.set_pad_rules(key, None)


def test_fused_ivf_dispatch_merge_is_pad_exempt(monkeypatch):
    """The fused path's only select_k calls are the XLA coarse probe
    selection (a real slab — pad rules apply) and the overflow merge over
    the in-kernel carry (already selected — MUST be pad-exempt)."""
    rng = np.random.default_rng(7)
    db = np.concatenate([
        rng.standard_normal((400, 16)).astype(np.float32),
        rng.standard_normal((120, 16)).astype(np.float32) * 0.05 + 2.0])
    q = rng.standard_normal((5, 16)).astype(np.float32)
    idx = ivf_flat.build(db, ivf_flat.IndexParams(
        n_lists=8, list_pad_expansion=1.01))
    assert idx.overflow_data.shape[0] > 0

    calls = []
    real = ivf_flat.select_k

    def spy(values, k, *a, **kw):
        calls.append(kw.get("pad_rules", True))
        return real(values, k, *a, **kw)

    monkeypatch.setattr(ivf_flat, "select_k", spy)
    monkeypatch.setenv("RAFT_TPU_PALLAS_INTERPRET", "1")
    ivf_flat.search(idx, q, 10, ivf_flat.SearchParams(
        n_probes=4, scan_mode="pallas"))
    assert calls, "fused dispatch traced no select_k call"
    assert calls.count(False) >= 1, (
        "overflow merge over the in-kernel carry must pass pad_rules=False"
    )


# ------------------------------------------------------------- heavy shapes

@pytest.mark.slow
def test_fused_l2_topk_heavy_parity(rng):
    x = rng.standard_normal((128, 64)).astype(np.float32)
    y = rng.standard_normal((5000, 64)).astype(np.float32)
    v, i = pk.fused_l2_topk(x, y, 100, tm=64, tn=512, interpret=True)
    d = ((x[:, None, :] - y[None, :, :]) ** 2).sum(-1)
    _assert_topk_match(v, i, d, 100, atol=1e-3)


@pytest.mark.slow
def test_fused_ivf_topk_heavy_parity(rng):
    L, pad, rot, nq, P, k = 16, 128, 64, 32, 8, 64
    data = rng.standard_normal((L, pad, rot)).astype(np.float32)
    ids = np.arange(L * pad, dtype=np.int32).reshape(L, pad)
    norms = (data ** 2).sum(-1)
    probes = rng.integers(0, L, (nq, P)).astype(np.int32)
    qres = rng.standard_normal((nq, P, rot)).astype(np.float32)
    qn = (qres ** 2).sum(-1)
    v, i = pk.fused_ivf_topk(probes, qres, qn, data, norms, ids, k,
                             pad_tile=32, clamp=True, interpret=True)
    ref_d, ref_gid = _ivf_ref(probes, qres, data, norms, ids, clamp=True)
    _assert_ivf_match(v, i, ref_d, ref_gid, k, atol=1e-3)
