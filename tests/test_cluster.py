"""Cluster tests: Lloyd k-means vs sklearn quality; balanced k-means balance
properties (reference pattern: cpp/test/cluster/kmeans.cu,
kmeans_balanced.cu — quality + balance assertions, not bitwise)."""

import numpy as np
import pytest
import jax

from raft_tpu.cluster import kmeans, kmeans_balanced, KMeansParams, KMeansBalancedParams
from raft_tpu.ops import rng as rrng


@pytest.fixture(scope="module")
def blobs():
    x, labels = rrng.make_blobs(3, 2000, 16, n_clusters=8, cluster_std=0.5)
    return np.asarray(x), np.asarray(labels)


class TestKMeans:
    def test_fit_quality_vs_sklearn(self, blobs):
        from sklearn.cluster import KMeans as SKKMeans

        x, _ = blobs
        params = KMeansParams(n_clusters=8, max_iter=100, seed=0)
        centers, labels, inertia, n_iter = kmeans.fit(x, params)
        sk = SKKMeans(n_clusters=8, n_init=3, max_iter=100, random_state=0).fit(x)
        assert float(inertia) <= sk.inertia_ * 1.1
        assert int(n_iter) < 100  # converged by tol

    def test_predict_matches_fit_labels(self, blobs):
        x, _ = blobs
        centers, labels, _, _ = kmeans.fit(x, KMeansParams(n_clusters=8, seed=1))
        labels2, _ = kmeans.predict(centers, x)
        assert (np.asarray(labels) == np.asarray(labels2)).mean() > 0.999

    def test_random_init(self, blobs):
        x, _ = blobs
        params = KMeansParams(n_clusters=8, init="random", seed=2, max_iter=50)
        centers, _, inertia, _ = kmeans.fit(x, params)
        assert centers.shape == (8, 16)
        assert np.isfinite(float(inertia))

    def test_init_from_array(self, blobs):
        x, _ = blobs
        init = x[:8].copy()
        params = KMeansParams(n_clusters=8, init="array", max_iter=20)
        centers, _, inertia, _ = kmeans.fit(x, params, init_centers=init)
        assert np.isfinite(float(inertia))

    def test_cluster_cost(self, blobs):
        x, _ = blobs
        centers, _, inertia, _ = kmeans.fit(x, KMeansParams(n_clusters=8, seed=0))
        cost = kmeans.cluster_cost(x, centers)
        assert float(cost) == pytest.approx(float(inertia), rel=1e-3)


class TestKMeansBalanced:
    def test_build_clusters_balance(self, blobs):
        x, _ = blobs
        key = jax.random.key(0)
        centers, labels, sizes = kmeans_balanced.build_clusters(
            key, x, 16, KMeansBalancedParams(n_iters=20)
        )
        sizes = np.asarray(sizes)
        assert sizes.sum() == len(x)
        # balance: no cluster starving below 25% of average (the adjust
        # threshold) after convergence, and none grotesquely oversized
        avg = len(x) / 16
        assert sizes.min() >= 0.25 * avg * 0.5  # slack for randomness
        assert sizes.max() <= 4 * avg

    def test_hierarchical_fit(self, blobs):
        x, _ = blobs
        key = jax.random.key(1)
        centers = kmeans_balanced.fit(key, x, 64, KMeansBalancedParams(n_iters=10))
        assert centers.shape == (64, 16)
        labels = np.asarray(kmeans_balanced.predict(centers, x))
        sizes = np.bincount(labels, minlength=64)
        # hierarchical balanced build: most clusters populated
        assert (sizes > 0).sum() >= 56
        avg = len(x) / 64
        assert sizes.max() <= 8 * avg

    def test_fit_predict_quality(self, blobs):
        x, true_labels = blobs
        key = jax.random.key(2)
        centers, labels = kmeans_balanced.fit_predict(
            key, x, 8, KMeansBalancedParams(n_iters=20)
        )
        # clustering should recover the 8 blobs (high ARI)
        from sklearn.metrics import adjusted_rand_score

        ari = adjusted_rand_score(true_labels, np.asarray(labels))
        assert ari > 0.9

    def test_inner_product_metric(self, blobs):
        x, _ = blobs
        xn = x / np.linalg.norm(x, axis=1, keepdims=True)
        key = jax.random.key(3)
        params = KMeansBalancedParams(n_iters=10, metric="inner_product")
        centers, labels, sizes = kmeans_balanced.build_clusters(key, xn, 8, params)
        # labels must be the argmax inner product against the (normalized)
        # centers the final E-step saw; the loop ends with an M-step so the
        # returned centers are means — normalize before comparing
        c = np.asarray(centers)
        cn = c / np.maximum(np.linalg.norm(c, axis=1, keepdims=True), 1e-20)
        assert ((xn @ cn.T).argmax(1) == np.asarray(labels)).mean() > 0.95
        assert float(np.asarray(sizes).sum()) == pytest.approx(len(xn))

    def test_weighted_rows_excluded(self, blobs):
        x, _ = blobs
        n = len(x)
        xpad = np.concatenate([x, 1e6 * np.ones((100, x.shape[1]), np.float32)])
        w = np.concatenate([np.ones(n, np.float32), np.zeros(100, np.float32)])
        key = jax.random.key(4)
        centers, _, sizes = kmeans_balanced.build_clusters(
            key, xpad, 8, KMeansBalancedParams(n_iters=10), weights=np.asarray(w)
        )
        # padded garbage rows must not pull any center to 1e6 range
        assert np.abs(np.asarray(centers)).max() < 1e3
        assert float(np.asarray(sizes).sum()) == pytest.approx(n)

    def test_bad_metric_raises(self):
        with pytest.raises(ValueError):
            KMeansBalancedParams(metric="canberra")


def test_kmeans_sample_weights(rng):
    from raft_tpu.cluster import kmeans

    # two blobs; heavily weight one point far away so it pulls its center
    x = np.vstack([rng.standard_normal((50, 2)),
                   rng.standard_normal((50, 2)) + 20.0]).astype(np.float32)
    w = np.ones(100, np.float32)
    centers, labels, inertia, _ = kmeans.fit(
        x, kmeans.KMeansParams(n_clusters=2, seed=3), sample_weights=w)
    c = np.sort(np.asarray(centers)[:, 0])
    assert abs(c[0]) < 2 and abs(c[1] - 20) < 2
    # weighted fit matches unweighted when weights are uniform
    cu, _, iu, _ = kmeans.fit(x, kmeans.KMeansParams(n_clusters=2, seed=3))
    np.testing.assert_allclose(np.asarray(inertia), np.asarray(iu), rtol=1e-4)


def test_update_centroids(rng):
    from raft_tpu.cluster import kmeans

    x = rng.standard_normal((60, 3)).astype(np.float32)
    c0 = x[:4].copy()
    w = rng.random(60).astype(np.float32) + 0.5
    new_c, wsum = kmeans.update_centroids(x, c0, sample_weights=w)
    # numpy reference
    d = ((x[:, None, :] - c0[None, :, :]) ** 2).sum(-1)
    lab = d.argmin(1)
    ref_c = np.vstack([
        (x[lab == j] * w[lab == j, None]).sum(0) / w[lab == j].sum()
        if (lab == j).any() else c0[j]
        for j in range(4)])
    np.testing.assert_allclose(np.asarray(new_c), ref_c, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(wsum), np.bincount(lab, w, 4).astype(np.float32), rtol=1e-5)


def test_kmeans_balanced_cv_target():
    """VERDICT r2 #2 gate: the balance polish must land the size CV at or
    under 0.25 on clustered data (the bench target's regime, scaled)."""
    from raft_tpu import Resources
    from raft_tpu.bench.datagen import low_rank_clusters

    rng = np.random.default_rng(0)
    n, dim, n_clusters = 20_000, 64, 256
    x = low_rank_clusters(rng, n, dim, n_centers=n_clusters // 4)
    res = Resources(seed=0)
    params = KMeansBalancedParams(n_iters=10)
    centers = kmeans_balanced.fit(res.next_key(), x, n_clusters, params,
                                  res=res)
    labels = kmeans_balanced.predict(centers, x, params, res=res)
    sizes = np.bincount(np.asarray(labels), minlength=n_clusters)
    cv = sizes.std() / sizes.mean()
    assert cv <= 0.25, cv
    # and the polish must be skippable (reference-faithful mode)
    params_off = KMeansBalancedParams(n_iters=10, target_balance_cv=None)
    centers_off = kmeans_balanced.fit(Resources(seed=0).next_key(), x,
                                      n_clusters, params_off,
                                      res=Resources(seed=0))
    assert centers_off.shape == centers.shape
