"""graftcheck --flow suite: F001–F005 on one-violation fixture twins,
the CFG walker's path/exception-edge semantics, the repo gate (every
live finding fixed or baseline-justified), the non-vacuity counters,
and the CLI rc/--rules/--json contract."""
import json
import os

import pytest
from graftcheck_util import (REPO, check_suppression, check_twin,
                             fixture_mod as _mod, inject, run_cli, tmp_mod)

from raft_tpu.analysis import (FLOW_RULES, flow_stats, load_baseline,
                               run_flow, split_by_baseline)
from raft_tpu.analysis.flow import (FlowContext, rule_resource_lifecycle,
                                    rule_settle_discipline,
                                    rule_swallowed_exception,
                                    rule_unbudgeted_blocking,
                                    rule_untyped_raise)

RULES = {"F001": rule_untyped_raise, "F002": rule_settle_discipline,
         "F003": rule_swallowed_exception, "F004": rule_resource_lifecycle,
         "F005": rule_unbudgeted_blocking}


def _run(rule):
    # flow rules take (mod, ctx); the fixture's own __all__ is the
    # typed-export fallback when no serving package is in scope
    return lambda mod: rule(mod, FlowContext([mod]))


# ------------------------------------------------------------ F-rule twins

@pytest.mark.parametrize("rule_id,stem,expect_qual", [
    ("F001", "f001", "lookup"),
    ("F002", "f002", "finish"),
    ("F003", "f003", "drain"),
    ("F004", "f004", "Pump._worker"),
    ("F005", "f005", "Client.fetch"),
], ids=list(RULES))
def test_rule_flags_bad_and_passes_clean(rule_id, stem, expect_qual):
    check_twin(_run(RULES[rule_id]), rule_id, stem, expect_qual)


def test_clean_twins_pass_every_flow_rule():
    for stem in ("f001", "f002", "f003", "f004", "f005"):
        mod = _mod(f"{stem}_clean.py")
        ctx = FlowContext([mod])
        for rule in FLOW_RULES:
            assert rule(mod, ctx) == [], (stem, rule.__name__)


@pytest.mark.parametrize("rule_id,fname,anchor", [
    ("F001", "f001_bad.py", "# untyped: the finding"),
    ("F002", "f002_bad.py", "# the no-outcome path leaks fut unsettled"),
    ("F003", "f003_bad.py", "except Exception:"),
    ("F004", "f004_bad.py",
     "self._worker = threading.Thread(target=self._run, daemon=True)"),
    ("F005", "f005_bad.py", "# the finding: unbudgeted block"),
], ids=list(RULES))
def test_inline_suppression(tmp_path, rule_id, fname, anchor):
    check_suppression(_run(RULES[rule_id]), tmp_path, fname, anchor, rule_id)


# ------------------------------------------- F001 str(e) matching finding

def test_f001_str_e_matching_is_its_own_finding(tmp_path):
    src = (
        '__all__ = ["BoomError"]\n\n\n'
        "class BoomError(Exception):\n"
        "    pass\n\n\n"
        "def classify(op):\n"
        "    try:\n"
        "        op()\n"
        "    except Exception as e:\n"
        '        if "shard" in str(e):\n'
        '            raise BoomError("shard")\n'
        '        raise BoomError("other")\n'
    )
    mod = tmp_mod(tmp_path, "stre.py", src)
    found = rule_untyped_raise(mod, FlowContext([mod]))
    assert [(f.rule, f.qualname) for f in found] == [("F001", "classify")]
    assert "matching" in found[0].message and "str(" in found[0].message


# ----------------------------------------------- F002 CFG path semantics

def test_f002_double_settle_without_once_guard(tmp_path):
    src = (
        "def finish(fut, a, b):\n"
        "    fut.set_result(a)\n"
        "    fut.set_result(b)\n"
    )
    mod = tmp_mod(tmp_path, "double.py", src)
    found = rule_settle_discipline(mod, FlowContext([mod]))
    assert [(f.rule, f.qualname) for f in found] == [("F002", "finish")]
    assert "settled twice" in found[0].message


def test_f002_once_guard_accepts_double_settle_race(tmp_path):
    src = (
        "def finish(fut, a, b):\n"
        "    try:\n"
        "        fut.set_result(a)\n"
        "        fut.set_result(b)\n"
        "    except InvalidStateError:\n"
        "        pass\n"
    )
    mod = tmp_mod(tmp_path, "guarded.py", src)
    assert rule_settle_discipline(mod, FlowContext([mod])) == []


def test_f002_early_return_before_local_future_exists(tmp_path):
    # the Fleet._attempt shape: a shed path returns before the future is
    # ever created — that path owes nothing
    src = (
        "def attempt(pool, req):\n"
        "    if req.expired:\n"
        "        return None\n"
        "    fut = pool.submit(req)\n"
        "    fut.add_done_callback(req.on_done)\n"
    )
    mod = tmp_mod(tmp_path, "early.py", src)
    assert rule_settle_discipline(mod, FlowContext([mod])) == []


def test_f002_exception_edge_is_a_path(tmp_path):
    # settling only in the try body leaks the future when compute raises
    src = (
        "def finish(fut, compute):\n"
        "    try:\n"
        "        fut.set_result(compute())\n"
        "    except Exception:\n"
        "        return None\n"
    )
    mod = tmp_mod(tmp_path, "edge.py", src)
    found = rule_settle_discipline(mod, FlowContext([mod]))
    assert [(f.rule, f.qualname) for f in found] == [("F002", "finish")]
    assert "unsettled" in found[0].message


def test_f002_handler_settle_covers_the_exception_edge(tmp_path):
    src = (
        "def finish(fut, compute):\n"
        "    try:\n"
        "        fut.set_result(compute())\n"
        "    except Exception as e:\n"
        "        fut.set_exception(e)\n"
    )
    mod = tmp_mod(tmp_path, "covered.py", src)
    assert rule_settle_discipline(mod, FlowContext([mod])) == []


# -------------------------------------------------- F004 reclaim variants

def test_f004_missing_stop_method_message(tmp_path):
    src = (
        "import threading\n\n\n"
        "class Leaky:\n"
        "    def __init__(self):\n"
        "        self._t = threading.Thread(target=print)\n"
    )
    mod = tmp_mod(tmp_path, "leaky.py", src)
    found = rule_resource_lifecycle(mod, FlowContext([mod]))
    assert [(f.rule, f.qualname) for f in found] == [("F004", "Leaky._t")]
    assert "no stop/close" in found[0].message


def test_f004_reclaim_through_helper_reached_from_stop(tmp_path):
    src = (
        "import threading\n\n\n"
        "class Pump:\n"
        "    def __init__(self):\n"
        "        self._t = threading.Thread(target=print)\n\n"
        "    def _teardown(self):\n"
        "        self._t.join()\n\n"
        "    def stop(self):\n"
        "        self._teardown()\n"
    )
    mod = tmp_mod(tmp_path, "helper.py", src)
    assert rule_resource_lifecycle(mod, FlowContext([mod])) == []


# ------------------------------------------------- F005 budget derivation

def test_f005_literal_timeout_is_flagged(tmp_path):
    src = (
        "class C:\n"
        "    def fetch(self, pool, q):\n"
        "        return pool.submit(q).result(timeout=30.0)\n"
    )
    mod = tmp_mod(tmp_path, "lit.py", src)
    found = rule_unbudgeted_blocking(mod, FlowContext([mod]))
    assert [(f.rule, f.qualname) for f in found] == [("F005", "C.fetch")]
    assert "literal timeout 30.0" in found[0].message


def test_f005_mapping_get_is_not_a_blocking_get(tmp_path):
    src = (
        "class C:\n"
        "    def tally(self, counts, key):\n"
        "        return counts.get(key, 0)\n"
    )
    mod = tmp_mod(tmp_path, "mapget.py", src)
    assert rule_unbudgeted_blocking(mod, FlowContext([mod])) == []


def test_f005_queue_get_with_bool_block_and_literal_timeout(tmp_path):
    src = (
        "class C:\n"
        "    def take(self, q):\n"
        "        return q.get(True, 5)\n"
    )
    mod = tmp_mod(tmp_path, "qget.py", src)
    found = rule_unbudgeted_blocking(mod, FlowContext([mod]))
    assert [(f.rule, f.qualname) for f in found] == [("F005", "C.take")]


def test_f005_lifecycle_methods_are_exempt(tmp_path):
    # stop() may block unbudgeted: shutdown is not the request path
    src = (
        "class C:\n"
        "    def stop(self):\n"
        "        self._worker.join()\n"
    )
    mod = tmp_mod(tmp_path, "lifecycle.py", src)
    assert rule_unbudgeted_blocking(mod, FlowContext([mod])) == []


# --------------------------------------------------------------- the gate

def test_repo_is_clean_under_committed_baseline():
    findings = run_flow(REPO)
    baseline = load_baseline(os.path.join(REPO, "graftcheck_baseline.json"))
    new, _ = split_by_baseline(findings, baseline)
    assert new == [], "\n".join(f.format() for f in new)


def test_flow_sweep_is_not_vacuous():
    # a resolver regression must not pass as "zero findings" silently:
    # the sweep must have actually seen the serving fabric
    s = flow_stats(REPO)
    assert s["modules"] >= 10, s
    assert s["raise_sites"] >= 5, s
    assert s["settle_owners"] >= 3, s
    assert s["resources"] >= 3, s


def test_cli_flow_nonzero_on_injected_violation(tmp_path):
    root = inject(tmp_path, "f001_bad.py", subdir="raft_tpu/serving")
    proc = run_cli("--root", root, "--no-baseline", "--flow")
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "F001" in proc.stdout and "lookup" in proc.stdout
    assert "[flow]" in proc.stdout  # the sweep stats line


def test_cli_rules_filter_scopes_the_gate(tmp_path):
    root = inject(tmp_path, "f001_bad.py", subdir="raft_tpu/serving")
    proc = run_cli("--root", root, "--no-baseline", "--flow",
                   "--rules", "F001")
    assert proc.returncode == 1, proc.stdout + proc.stderr
    proc = run_cli("--root", root, "--no-baseline", "--flow",
                   "--rules", "F002")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "F001" not in [ln[:4] for ln in proc.stdout.splitlines()]


def test_cli_without_flow_skips_f_rules(tmp_path):
    root = inject(tmp_path, "f001_bad.py", subdir="raft_tpu/serving")
    proc = run_cli("--root", root, "--no-baseline")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "F001" not in proc.stdout


# ------------------------------------------------------------- --json dump

def test_cli_json_dump_and_baselined_flag(tmp_path):
    root = inject(tmp_path, "f001_bad.py", subdir="raft_tpu/serving")
    baseline = tmp_path / "baseline.json"
    out = tmp_path / "findings.json"

    proc = run_cli("--root", root, "--flow", "--baseline", str(baseline),
                   "--json", str(out))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    doc = json.loads(out.read_text())
    assert doc["version"] == 1
    (f,) = [e for e in doc["findings"] if e["rule"] == "F001"]
    assert f["qualname"] == "lookup" and f["baselined"] is False
    assert f["file"].endswith("injected.py") and f["line"] > 0
    assert "RuntimeError" in f["message"]

    # record + justify the baseline: same finding now dumps as baselined
    proc = run_cli("--root", root, "--flow", "--baseline", str(baseline),
                   "--update-baseline")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    bdoc = json.loads(baseline.read_text())
    for e in bdoc["entries"]:
        e["justification"] = "fixture: exercises the --json baselined flag"
    baseline.write_text(json.dumps(bdoc))
    proc = run_cli("--root", root, "--flow", "--baseline", str(baseline),
                   "--json", str(out))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(out.read_text())
    (f,) = [e for e in doc["findings"] if e["rule"] == "F001"]
    assert f["baselined"] is True


def test_cli_json_to_stdout(tmp_path):
    root = inject(tmp_path, "f001_bad.py", subdir="raft_tpu/serving")
    proc = run_cli("--root", root, "--no-baseline", "--flow", "-q",
                   "--json", "-")
    assert proc.returncode == 1
    # the summary line follows the JSON document on stdout
    doc, _ = json.JSONDecoder().raw_decode(
        proc.stdout, proc.stdout.index("{"))
    assert any(e["rule"] == "F001" for e in doc["findings"])
