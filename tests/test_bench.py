"""Benchmark harness tests — config-driven run over a tiny dataset with
all four algos (reference: raft-ann-bench run/data_export/plot CLIs)."""

import json
import os

import numpy as np
import pytest

from raft_tpu import native
from raft_tpu.bench import export, runner


@pytest.fixture(scope="module")
def dataset_files(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("bench_data")
    rng = np.random.default_rng(11)
    base = rng.standard_normal((2000, 24)).astype(np.float32)
    queries = rng.standard_normal((100, 24)).astype(np.float32)
    bp = str(tmp / "base.fbin")
    qp = str(tmp / "query.fbin")
    native.write_bin(bp, base)
    native.write_bin(qp, queries)
    gt = runner.generate_groundtruth(base, queries, 10, "euclidean")
    gp = str(tmp / "gt.ibin")
    native.write_bin(gp, gt.astype(np.int32))
    return {"base": bp, "query": qp, "gt": gp}


def _config(files, indexes):
    return {
        "dataset": {
            "name": "tiny-24-euclidean",
            "base_file": files["base"],
            "query_file": files["query"],
            "groundtruth_neighbors_file": files["gt"],
            "distance": "euclidean",
        },
        "index": indexes,
    }


def test_shipped_configs_are_valid():
    """The configs under bench/conf (the reference's run/conf role) must
    parse into DatasetSpec + registered algos, with every search-param
    dtype key accepted by the validators."""
    import glob
    import pathlib

    conf_dir = pathlib.Path(runner.__file__).parent / "conf"
    confs = sorted(glob.glob(str(conf_dir / "*.json")))
    assert confs, "no shipped bench configs found"
    for path in confs:
        cfg = json.load(open(path))
        runner.DatasetSpec(**cfg["dataset"])
        for idx in cfg["index"]:
            assert idx["algo"] in runner.ALGOS, (path, idx["algo"])
            for sp in idx.get("search_params", [{}]):
                runner._scan_dtype(sp)
                runner._internal_distance_dtype(sp)
                runner._lut_dtype(sp)
                assert sp.get("scan_mode", "auto") in ("auto", "cache",
                                                       "lut"), (path, sp)


def test_competitor_wrappers_comparative_run(dataset_files, tmp_path):
    """Cross-library comparison in ONE run (the faiss/hnswlib wrapper role,
    bench/ann/src/faiss/faiss_wrapper.h): raft_tpu vs sklearn brute force
    vs a KD-tree through the same AnnAlgo seam, so QPS-vs-recall exports
    are comparative rather than self-referential (VERDICT r1 missing #2)."""
    config = _config(dataset_files, [
        {"name": "bf", "algo": "raft_brute_force", "build_param": {},
         "search_params": [{}]},
        {"name": "sk", "algo": "sklearn_brute_force", "build_param": {},
         "search_params": [{}]},
        {"name": "kd", "algo": "scipy_kdtree",
         "build_param": {"leafsize": 16},
         "search_params": [{"eps": 0.0}, {"eps": 0.5}]},
    ])
    rows = runner.run_benchmark(config, k=10, search_iters=1)
    by_name = {}
    for r in rows:
        by_name.setdefault(r["name"], []).append(r)
    assert len(rows) == 4
    # exact algorithms agree on recall; every row carries both bench modes
    assert by_name["bf"][0]["recall"] >= 0.999
    assert by_name["sk"][0]["recall"] >= 0.999
    assert by_name["kd"][0]["recall"] >= 0.999  # the eps=0 row is exact
    for r in rows:
        assert r["qps"] > 0 and r["qps_latency_mode"] > 0
        assert r["latency_ms"] > 0


def test_hnsw_cpu_competitor(dataset_files):
    """The hnswlib-role rival (native C++ layer-0 ef-search over a CAGRA
    graph, hnswlib_wrapper.h analog): higher ef must trade QPS for
    recall, and big-ef recall must be near-exact on a tiny set."""
    config = _config(dataset_files, [
        {"name": "hnsw", "algo": "hnsw_cpu", "build_param": {"M": 8},
         "search_params": [{"ef": 10}, {"ef": 200}]},
    ])
    rows = runner.run_benchmark(config, k=10, search_iters=1)
    assert len(rows) == 2
    lo, hi = rows
    assert hi["recall"] >= 0.95, hi
    assert hi["recall"] >= lo["recall"]


@pytest.mark.slow
def test_run_all_algos(dataset_files, tmp_path):
    config = _config(dataset_files, [
        {"name": "bf", "algo": "raft_brute_force", "build_param": {},
         "search_params": [{}]},
        {"name": "ivf_flat.n16", "algo": "raft_ivf_flat",
         "build_param": {"nlist": 16},
         "search_params": [{"nprobe": 4}, {"nprobe": 16}]},
        {"name": "ivf_pq.n16", "algo": "raft_ivf_pq",
         "build_param": {"nlist": 16, "pq_dim": 8},
         "search_params": [{"nprobe": 16, "smemLutDtype": "fp16"}]},
        {"name": "cagra.d16", "algo": "raft_cagra",
         "build_param": {"graph_degree": 16,
                         "intermediate_graph_degree": 24},
         "search_params": [{"itopk": 32}]},
    ])
    out = str(tmp_path / "results.jsonl")
    rows = runner.run_benchmark(config, k=10, search_iters=1, out_path=out)
    assert len(rows) == 5
    by_name = {}
    for r in rows:
        by_name.setdefault(r["name"], []).append(r)
    assert by_name["bf"][0]["recall"] >= 0.999
    assert by_name["ivf_flat.n16"][1]["recall"] >= 0.999  # full probe
    assert by_name["ivf_flat.n16"][0]["recall"] <= by_name[
        "ivf_flat.n16"][1]["recall"] + 1e-6
    assert by_name["ivf_pq.n16"][0]["recall"] >= 0.5
    assert by_name["cagra.d16"][0]["recall"] >= 0.8
    for r in rows:
        assert r["qps"] > 0 and r["build_time"] >= 0

    # jsonl round-trips
    loaded = export.load_results(out)
    assert len(loaded) == 5

    # csv + pareto + plot
    csv_path = str(tmp_path / "out.csv")
    export.export_csv(loaded, csv_path, pareto=True)
    assert os.path.getsize(csv_path) > 0
    png = str(tmp_path / "plot.png")
    export.plot(loaded, png)
    assert os.path.getsize(png) > 0


def test_refine_ratio_path(dataset_files):
    config = _config(dataset_files, [
        {"name": "pq_refined", "algo": "raft_ivf_pq",
         "build_param": {"nlist": 16, "pq_dim": 4},
         "search_params": [{"nprobe": 16},
                           {"nprobe": 16, "refine_ratio": 4}]},
    ])
    rows = runner.run_benchmark(config, k=10, search_iters=1)
    plain, refined = rows[0], rows[1]
    # exact re-ranking must not hurt recall at heavy compression
    assert refined["recall"] >= plain["recall"]
    assert refined["recall"] >= 0.85


def test_pareto_frontier():
    rows = [{"recall": 0.9, "qps": 100}, {"recall": 0.95, "qps": 50},
            {"recall": 0.8, "qps": 120}, {"recall": 0.94, "qps": 40}]
    front = export.pareto_frontier(rows)
    assert {(r["recall"], r["qps"]) for r in front} == {
        (0.95, 50), (0.9, 100), (0.8, 120)}


def test_cli_get_dataset_and_groundtruth(tmp_path):
    """CLI subcommands: hdf5→fbin conversion, groundtruth generate + split
    (raft-ann-bench get_dataset / generate_groundtruth / split_groundtruth)."""
    import h5py

    from raft_tpu.bench.__main__ import main as cli
    from raft_tpu import native

    rng = np.random.default_rng(0)
    train = rng.standard_normal((300, 16)).astype(np.float32)
    test = rng.standard_normal((20, 16)).astype(np.float32)
    h5 = tmp_path / "toy-euclidean.hdf5"
    with h5py.File(h5, "w") as f:
        f["train"] = train
        f["test"] = test
    assert cli(["get-dataset", "--hdf5", str(h5),
                "--out", str(tmp_path)]) == 0
    base = native.read_bin(str(tmp_path / "toy-euclidean" / "base.fbin"))
    np.testing.assert_allclose(base, train, rtol=1e-6)

    gt_path = tmp_path / "gt.ibin"
    assert cli(["generate-groundtruth",
                "--base", str(tmp_path / "toy-euclidean" / "base.fbin"),
                "--queries", str(tmp_path / "toy-euclidean" / "query.fbin"),
                "--out", str(gt_path), "--k", "5"]) == 0
    gt = native.read_bin(str(gt_path), dtype=np.int32)
    ref = np.argsort(((test[:, None] - train[None]) ** 2).sum(-1), 1)[:, :5]
    np.testing.assert_array_equal(gt, ref)

    # documented subcommand-less form maps to `run` (README/getting_started)
    conf = {"dataset": {"name": "toy",
                        "base_file": str(tmp_path / "toy-euclidean" /
                                         "base.fbin"),
                        "query_file": str(tmp_path / "toy-euclidean" /
                                          "query.fbin"),
                        "distance": "euclidean"},
            "index": [{"name": "bf", "algo": "raft_brute_force",
                       "build_param": {}, "search_params": [{}]}]}
    conf_path = tmp_path / "conf.json"
    conf_path.write_text(json.dumps(conf))
    assert cli(["--conf", str(conf_path), "--k", "3",
                "--out", str(tmp_path / "res.jsonl")]) == 0

    # big-ann combined layout: header, uint32 id block, float32 dist block
    comb_path = tmp_path / "comb.bin"
    with open(comb_path, "wb") as f:
        np.asarray(ref.shape, np.int32).tofile(f)
        ref.astype(np.uint32).tofile(f)
        np.ones_like(ref, np.float32).tofile(f)
    assert cli(["split-groundtruth", "--gt", str(comb_path),
                "--out-prefix", str(tmp_path / "sp")]) == 0
    np.testing.assert_array_equal(
        native.read_bin(str(tmp_path / "sp.neighbors.ibin"), dtype=np.int32),
        ref)
    np.testing.assert_array_equal(
        native.read_bin(str(tmp_path / "sp.distances.fbin")),
        np.ones_like(ref, np.float32))


def test_cli_algos_filter_and_resume(dataset_files, tmp_path):
    """--algos restricts entries; --resume skips names already in the out
    JSONL and exports the merged set (the off-window baseline pre-run
    contract the queue's pareto step relies on)."""
    import subprocess
    import sys

    conf = _config(dataset_files, [
        {"name": "raft_brute_force", "algo": "raft_brute_force",
         "build_param": {}, "search_params": [{}]},
        {"name": "sklearn_brute_force", "algo": "sklearn_brute_force",
         "build_param": {}, "search_params": [{}]},
    ])
    cp = str(tmp_path / "conf.json")
    with open(cp, "w") as f:
        json.dump(conf, f)
    out = str(tmp_path / "rows.jsonl")
    csv = str(tmp_path / "rows.csv")
    env = dict(os.environ, JAX_PLATFORMS="cpu")

    def run(*extra):
        return subprocess.run(
            [sys.executable, "-m", "raft_tpu.bench", "run", "--conf", cp,
             "--out", out, "--csv", csv, "--iters", "1", *extra],
            capture_output=True, text=True, env=env, timeout=600)

    r1 = run("--algos", "sklearn")
    assert r1.returncode == 0, r1.stderr[-800:]
    rows = [json.loads(l) for l in open(out)]
    assert {r["name"] for r in rows} == {"sklearn_brute_force"}

    r2 = run("--resume")
    assert r2.returncode == 0, r2.stderr[-800:]
    assert "--resume: skipping completed ['sklearn_brute_force']" in r2.stdout
    rows = [json.loads(l) for l in open(out)]
    assert {r["name"] for r in rows} == {"raft_brute_force",
                                         "sklearn_brute_force"}
    # merged CSV carries both, resumed row included
    csv_text = open(csv).read()
    assert "sklearn_brute_force" in csv_text
    assert "raft_brute_force" in csv_text


def test_cli_resume_finishes_partial_entry(dataset_files, tmp_path):
    """--resume keys completion on (name, search_param), not name: a
    timeout kill mid-entry leaves some search-param rows missing, and the
    next resume must run exactly those (ADVICE r4 medium — a name-only
    key permanently dropped the rest of the pareto front)."""
    import subprocess
    import sys

    sps = [{}, {"scan_dtype": "bfloat16"}]
    conf = _config(dataset_files, [
        {"name": "bf", "algo": "raft_brute_force",
         "build_param": {}, "search_params": sps},
    ])
    cp = str(tmp_path / "conf.json")
    with open(cp, "w") as f:
        json.dump(conf, f)
    out = str(tmp_path / "rows.jsonl")

    # simulate the killed run: only the first search_param's row landed
    with open(out, "w") as f:
        f.write(json.dumps({"name": "bf", "algo": "raft_brute_force",
                            "qps": 1.0, "recall": 1.0,
                            "search_param": sps[0]}) + "\n")

    r = subprocess.run(
        [sys.executable, "-m", "raft_tpu.bench", "run", "--conf", cp,
         "--out", out, "--iters", "1", "--resume"],
        capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"), timeout=600)
    assert r.returncode == 0, r.stderr[-800:]
    assert "finishing partial" in r.stdout
    rows = [json.loads(l) for l in open(out)]
    params = [r["search_param"] for r in rows if r["name"] == "bf"]
    assert params == sps  # old row kept, ONLY the missing one re-run

    # a second resume now skips the entry entirely
    r2 = subprocess.run(
        [sys.executable, "-m", "raft_tpu.bench", "run", "--conf", cp,
         "--out", out, "--iters", "1", "--resume"],
        capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"), timeout=600)
    assert r2.returncode == 0, r2.stderr[-800:]
    assert "--resume: skipping completed ['bf']" in r2.stdout
    assert len([json.loads(l) for l in open(out)]) == 2


def test_cli_filters_tolerate_missing_name(dataset_files, tmp_path):
    """--algos/--resume must not KeyError on an index entry without a
    "name" key — the runner itself falls back to the algo name
    (ADVICE r4 low)."""
    import subprocess
    import sys

    conf = _config(dataset_files, [
        {"algo": "raft_brute_force", "build_param": {},
         "search_params": [{}]},
    ])
    cp = str(tmp_path / "conf.json")
    with open(cp, "w") as f:
        json.dump(conf, f)
    out = str(tmp_path / "rows.jsonl")
    r = subprocess.run(
        [sys.executable, "-m", "raft_tpu.bench", "run", "--conf", cp,
         "--out", out, "--iters", "1", "--resume",
         "--algos", "brute"],
        capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"), timeout=600)
    assert r.returncode == 0, r.stderr[-800:]
    rows = [json.loads(l) for l in open(out)]
    assert rows and rows[0]["name"] == "raft_brute_force"
