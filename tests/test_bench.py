"""Benchmark harness tests — config-driven run over a tiny dataset with
all four algos (reference: raft-ann-bench run/data_export/plot CLIs)."""

import json
import os

import numpy as np
import pytest

from raft_tpu import native
from raft_tpu.bench import export, runner


@pytest.fixture(scope="module")
def dataset_files(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("bench_data")
    rng = np.random.default_rng(11)
    base = rng.standard_normal((2000, 24)).astype(np.float32)
    queries = rng.standard_normal((100, 24)).astype(np.float32)
    bp = str(tmp / "base.fbin")
    qp = str(tmp / "query.fbin")
    native.write_bin(bp, base)
    native.write_bin(qp, queries)
    gt = runner.generate_groundtruth(base, queries, 10, "euclidean")
    gp = str(tmp / "gt.ibin")
    native.write_bin(gp, gt.astype(np.int32))
    return {"base": bp, "query": qp, "gt": gp}


def _config(files, indexes):
    return {
        "dataset": {
            "name": "tiny-24-euclidean",
            "base_file": files["base"],
            "query_file": files["query"],
            "groundtruth_neighbors_file": files["gt"],
            "distance": "euclidean",
        },
        "index": indexes,
    }


def test_run_all_algos(dataset_files, tmp_path):
    config = _config(dataset_files, [
        {"name": "bf", "algo": "raft_brute_force", "build_param": {},
         "search_params": [{}]},
        {"name": "ivf_flat.n16", "algo": "raft_ivf_flat",
         "build_param": {"nlist": 16},
         "search_params": [{"nprobe": 4}, {"nprobe": 16}]},
        {"name": "ivf_pq.n16", "algo": "raft_ivf_pq",
         "build_param": {"nlist": 16, "pq_dim": 8},
         "search_params": [{"nprobe": 16, "smemLutDtype": "fp16"}]},
        {"name": "cagra.d16", "algo": "raft_cagra",
         "build_param": {"graph_degree": 16,
                         "intermediate_graph_degree": 24},
         "search_params": [{"itopk": 32}]},
    ])
    out = str(tmp_path / "results.jsonl")
    rows = runner.run_benchmark(config, k=10, search_iters=1, out_path=out)
    assert len(rows) == 5
    by_name = {}
    for r in rows:
        by_name.setdefault(r["name"], []).append(r)
    assert by_name["bf"][0]["recall"] >= 0.999
    assert by_name["ivf_flat.n16"][1]["recall"] >= 0.999  # full probe
    assert by_name["ivf_flat.n16"][0]["recall"] <= by_name[
        "ivf_flat.n16"][1]["recall"] + 1e-6
    assert by_name["ivf_pq.n16"][0]["recall"] >= 0.5
    assert by_name["cagra.d16"][0]["recall"] >= 0.8
    for r in rows:
        assert r["qps"] > 0 and r["build_time"] >= 0

    # jsonl round-trips
    loaded = export.load_results(out)
    assert len(loaded) == 5

    # csv + pareto + plot
    csv_path = str(tmp_path / "out.csv")
    export.export_csv(loaded, csv_path, pareto=True)
    assert os.path.getsize(csv_path) > 0
    png = str(tmp_path / "plot.png")
    export.plot(loaded, png)
    assert os.path.getsize(png) > 0


def test_refine_ratio_path(dataset_files):
    config = _config(dataset_files, [
        {"name": "pq_refined", "algo": "raft_ivf_pq",
         "build_param": {"nlist": 16, "pq_dim": 4},
         "search_params": [{"nprobe": 16},
                           {"nprobe": 16, "refine_ratio": 4}]},
    ])
    rows = runner.run_benchmark(config, k=10, search_iters=1)
    plain, refined = rows[0], rows[1]
    # exact re-ranking must not hurt recall at heavy compression
    assert refined["recall"] >= plain["recall"]
    assert refined["recall"] >= 0.85


def test_pareto_frontier():
    rows = [{"recall": 0.9, "qps": 100}, {"recall": 0.95, "qps": 50},
            {"recall": 0.8, "qps": 120}, {"recall": 0.94, "qps": 40}]
    front = export.pareto_frontier(rows)
    assert {(r["recall"], r["qps"]) for r in front} == {
        (0.95, 50), (0.9, 100), (0.8, 120)}
