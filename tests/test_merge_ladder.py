"""Merge-ladder bit-identity on the 8-device virtual CPU mesh.

Every cross-chip merge schedule (all_gather reference, log2(S) ppermute
tree, neighbor ring) must return byte-identical (distances, indices) —
the lex-merge construction in ``parallel/comms.py`` makes any schedule
equal to a stable ``select_k`` over the rank-ordered concat, so the
dispatch choice is purely a bandwidth decision (docs/sharding.md).
"""

import os

import numpy as np
import pytest

import jax

from raft_tpu.neighbors import brute_force
from raft_tpu.parallel import comms as comms_mod
from raft_tpu.parallel import sharded


@pytest.fixture(scope="module")
def comms():
    assert len(jax.devices()) == 8, "conftest should provide 8 CPU devices"
    return comms_mod.init_comms(axis="data")


def _ladder(search, modes=("allgather", "tree", "ring")):
    """Run ``search(merge_mode)`` for each mode; assert all byte-equal."""
    d_ref, i_ref = (np.asarray(a) for a in search(modes[0]))
    for mode in modes[1:]:
        d, i = (np.asarray(a) for a in search(mode))
        np.testing.assert_array_equal(d, d_ref, err_msg=f"{mode} dists")
        np.testing.assert_array_equal(i, i_ref, err_msg=f"{mode} ids")
    return d_ref, i_ref


# ------------------------------------------------------------ brute force


def test_knn_merge_ladder(comms):
    rng = np.random.default_rng(0)
    db = rng.standard_normal((1000, 16)).astype(np.float32)
    q = rng.standard_normal((16, 16)).astype(np.float32)
    d, i = _ladder(lambda m: sharded.knn(comms, q, db, k=10, merge_mode=m))
    d1, i1 = brute_force.knn(q, db, k=10, metric="sqeuclidean")
    np.testing.assert_array_equal(i, np.asarray(i1))


def test_knn_merge_ladder_ragged_last_shard(comms):
    # 1003 rows over 8 shards: np.linspace bounds give a ragged split and
    # the local scan pads — padding rows must never leak through any merge
    rng = np.random.default_rng(1)
    db = rng.standard_normal((1003, 16)).astype(np.float32)
    q = rng.standard_normal((8, 16)).astype(np.float32)
    d, i = _ladder(lambda m: sharded.knn(comms, q, db, k=7, merge_mode=m))
    assert (i >= 0).all() and (i < 1003).all()


def test_knn_merge_ladder_duplicate_rows_across_shards(comms):
    # the same 128 vectors tiled onto every shard: every query's top-k is
    # one giant tie group, so bit-identity here proves the tie-break
    # (value, global-concat-position) is schedule-invariant
    rng = np.random.default_rng(2)
    base = rng.standard_normal((128, 8)).astype(np.float32)
    db = np.tile(base, (8, 1))
    q = base[:8] + 0.0
    _, i = _ladder(lambda m: sharded.knn(comms, q, db, k=10, merge_mode=m))
    # ties resolve to the lowest global row id first (stable order)
    assert (i[:, 0] == np.arange(8)).all()


# -------------------------------------------------------------- ivf_flat


@pytest.mark.slow
def test_ivf_flat_merge_ladder(comms):
    # slow: the sharded build + three merge variants cost ~30 s of compile
    # on the virtual mesh; the CI mesh job runs this file unfiltered
    from raft_tpu.neighbors import ivf_flat

    rng = np.random.default_rng(3)
    db = rng.standard_normal((1024, 16)).astype(np.float32)
    q = rng.standard_normal((8, 16)).astype(np.float32)
    idx = sharded.build_ivf_flat(comms, db, ivf_flat.IndexParams(n_lists=4))
    sp = ivf_flat.SearchParams(n_probes=2)
    # n_probes < n_lists leaves short lists ragged: id<0 slots must be
    # masked to +/-inf before any merge (plan.mask_invalid)
    _ladder(lambda m: sharded.search_ivf_flat(idx, q, 5, sp, merge_mode=m))


# ---------------------------------------------------------------- ivf_pq


@pytest.mark.slow
def test_ivf_pq_merge_ladder(comms):
    # slow for the same reason as the ivf_flat ladder above
    from raft_tpu.neighbors import ivf_pq

    rng = np.random.default_rng(4)
    db = rng.standard_normal((1024, 16)).astype(np.float32)
    q = rng.standard_normal((8, 16)).astype(np.float32)
    idx = sharded.build_ivf_pq(
        comms, db, ivf_pq.IndexParams(n_lists=4, pq_dim=8, kmeans_n_iters=3))
    sp = ivf_pq.SearchParams(n_probes=2)
    _ladder(lambda m: sharded.search_ivf_pq(idx, q, 5, sp, merge_mode=m))


# -------------------------------------------- pallas interpret ring shift


@pytest.mark.slow
def test_ring_merge_pallas_interpret_parity(comms, monkeypatch):
    """RAFT_TPU_PALLAS_INTERPRET=1 routes merge_mode='ring' through the
    Mosaic-interpreted RDMA kernel — results must match the XLA ppermute
    ring bit-for-bit (the CI parity hook for the TPU send path)."""
    rng = np.random.default_rng(5)
    db = rng.standard_normal((512, 8)).astype(np.float32)
    q = rng.standard_normal((4, 8)).astype(np.float32)
    d_x, i_x = sharded.knn(comms, q, db, k=5, merge_mode="ring")
    monkeypatch.setenv("RAFT_TPU_PALLAS_INTERPRET", "1")
    sharded.plan_cache_clear()
    try:
        plan = sharded.plan_sharded_search(
            comms, "brute_force", 512, (0, 512), 4, 5, 5, "xla",
            merge_mode="ring")
        assert plan.ring_shift == "pallas_interpret"
        d_p, i_p = sharded.knn(comms, q, db, k=5, merge_mode="ring")
        np.testing.assert_array_equal(np.asarray(d_p), np.asarray(d_x))
        np.testing.assert_array_equal(np.asarray(i_p), np.asarray(i_x))
    finally:
        monkeypatch.delenv("RAFT_TPU_PALLAS_INTERPRET", raising=False)
        sharded.plan_cache_clear()
        jax.clear_caches()  # drop interpret-mode pallas executables


# -------------------------------------------------- plan + dispatch rules


def test_merge_dispatch_matrix():
    # auto on CPU: pow2 -> tree, non-pow2 -> allgather (XOR pairing)
    assert sharded.merge_dispatch_explained("auto", 8)[:2] == \
        ("tree", "merge_tree")
    assert sharded.merge_dispatch_explained("auto", 6)[:2] == \
        ("allgather", "merge_allgather")
    assert sharded.merge_dispatch_explained("allgather", 6)[:2] == \
        ("allgather", "forced")
    with pytest.raises(ValueError, match="power-of-two"):
        sharded.merge_dispatch_explained("tree", 6)
    with pytest.raises(ValueError, match="at least 2"):
        sharded.merge_dispatch_explained("ring", 1)
    with pytest.raises(ValueError, match="unknown merge_mode"):
        sharded.merge_dispatch_explained("bogus", 8)


def test_plan_cache_round_trip(comms):
    sharded.plan_cache_clear()
    a = sharded.plan_sharded_search(comms, "brute_force", 1000,
                                    (0, 500, 1000), 16, 10, 10, "xla")
    b = sharded.plan_sharded_search(comms, "brute_force", 1000,
                                    (0, 500, 1000), 16, 10, 10, "xla")
    assert a is b  # cache hit returns the identical frozen plan
    ep = a.explain_plan()
    assert ep["merge_mode"] == "tree"
    assert ep["merge_bytes_tree"] < ep["merge_bytes_allgather"]


def test_sharded_search_emits_merge_dispatch_record(comms):
    from raft_tpu.obs import explain

    rng = np.random.default_rng(6)
    db = rng.standard_normal((256, 8)).astype(np.float32)
    q = rng.standard_normal((4, 8)).astype(np.float32)
    with explain.capture() as cap:
        sharded.knn(comms, q, db, k=3)
    recs = [r for r in cap.records if r.family == "sharded_brute_force"]
    assert recs, "sharded knn must record its merge dispatch"
    assert recs[-1].engine == "tree"
    assert recs[-1].reason == "merge_tree"
    assert recs[-1].plan["merge_mode"] == "tree"


# ------------------------------------------- compiled cross-chip bytes


def test_tree_merge_compiled_bytes_below_allgather(comms):
    """ISSUE 12 acceptance: the tree merge's compiled cross-chip receive
    bytes (parsed from HLO) are strictly below all_gather's at S=8."""
    from raft_tpu.obs import costs

    got = {}
    for name, make in costs.sharded_merge_entries(nq=64, kk=16, k=16):
        e = costs.compile_entry(name, make)
        assert e.collective_bytes, f"{name}: no collectives parsed"
        assert e.collective_drift_ratio is not None
        # the byte planner must stay calibrated (C001 discipline)
        assert 0.5 <= e.collective_drift_ratio <= 2.0, e.to_dict()
        got[name.split("@")[0]] = e.collective_bytes
    assert got["sharded_merge_tree"] < got["sharded_merge_allgather"]


# --------------------------------------------- degraded-coverage restore


@pytest.mark.slow
def test_coverage_below_one_restore_unaffected_by_plan_path(comms, tmp_path):
    # slow: pays the full sharded ivf_pq build compile (~40 s on the
    # 1-core container); the CI mesh job runs this file unfiltered
    """The PlacementPlan refactor must not disturb the elastic path: a
    7/8-coverage restore still searches host-side, excluding dead-shard
    ids (regression companion to test_faults.py's chaos suite)."""
    from raft_tpu.neighbors import ivf_pq

    rng = np.random.default_rng(7)
    db = rng.standard_normal((1024, 16)).astype(np.float32)
    q = rng.standard_normal((4, 16)).astype(np.float32)
    idx = sharded.build_ivf_pq(
        comms, db, ivf_pq.IndexParams(n_lists=4, pq_dim=8, kmeans_n_iters=3))
    prefix = str(tmp_path / "idx")
    sharded.serialize_ivf_pq(idx, prefix)
    dead = 5
    os.remove(f"{prefix}.rank{dead}")
    el = sharded.deserialize_ivf_pq_elastic(prefix, allow_partial=True)
    assert el.coverage == 7 / 8
    _, i = el.search(q, 5, ivf_pq.SearchParams(n_probes=4))
    ids = np.asarray(i)
    bounds = sharded.shard_bounds(8, 1024)
    lo, hi = bounds[dead], bounds[dead + 1]
    assert not np.any((ids >= lo) & (ids < hi))
