"""R005 clean twin: same intermediate, but the enclosing caller sizes the
tiles with the workspace solver, so the live set is budget-bounded."""
import jax
import jax.numpy as jnp

from raft_tpu.core.resources import solve_joint_tiles


@jax.jit
def gather_core(lut, idx, q_tile):
    g = jnp.zeros((q_tile, idx.shape[1], lut.shape[1]), jnp.float32)
    return g + lut[idx[:q_tile]]


def gather_search(lut, idx, budget):
    q_tile, p_tile = solve_joint_tiles(budget, lut.shape[1] * 4, idx.shape[1])
    return gather_core(lut, idx, q_tile)
