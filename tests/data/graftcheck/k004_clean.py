"""K004 clean twin: the interpret flag is passed through, never
branched on — identical behavior either way."""

import jax
from jax.experimental import pallas as pl


def run_vmem_bytes(rows, cols):
    """Live set: the input block plus the output block."""
    return 2 * rows * cols * 4


def _noop_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...]


def run(x, interpret=False):
    return pl.pallas_call(
        _noop_kernel,
        grid=(1,),
        in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=bool(interpret),
    )(x)
