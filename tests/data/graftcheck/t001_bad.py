"""T001 fixture: shared counter written from two thread entry points
with no guarded_by declaration — genuinely racy at runtime (the
read-modify-write spans two lines, so a preemption between them loses
increments), which is what tests/test_interleave.py demonstrates."""
import threading


class SharedCounter:
    def __init__(self):
        self._lock = threading.Lock()  # declared but never used
        self.count = 0

    def add(self, n):
        for _ in range(n):
            v = self.count
            self.count = v + 1

    def spin(self, n):
        t = threading.Thread(target=self.add, args=(n,))
        t.start()
        return t
