"""T002 fixture: two locks acquired in opposite orders by two public
methods — a classic ABBA deadlock waiting for the right interleaving."""
import threading


class Transfer:
    def __init__(self):
        self._debit_lock = threading.Lock()
        self._credit_lock = threading.Lock()
        self.debits = 0  # guarded_by: _debit_lock
        self.credits = 0  # guarded_by: _credit_lock

    def move(self, n):
        with self._debit_lock:
            with self._credit_lock:
                self.debits += n
                self.credits += n

    def refund(self, n):
        with self._credit_lock:
            with self._debit_lock:
                self.credits -= n
                self.debits -= n
