"""K004 bad twin: behavior forks on the interpret flag."""

from jax.experimental import pallas as pl  # noqa: F401


def dispatch(x, interpret=False):
    if interpret:
        return x
    return x * 2
