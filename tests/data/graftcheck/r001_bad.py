"""R001 fixture: host-sync inside a jit-traced function."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def pulls_to_host(x):
    y = jnp.sum(x * x)
    return np.asarray(y)  # device->host sync under trace
