"""R005 fixture: 3-symbolic-dim intermediate with no workspace solve."""
import jax
import jax.numpy as jnp


@jax.jit
def gathers_everything(lut, idx):
    g = jnp.zeros((idx.shape[0], idx.shape[1], lut.shape[1]), jnp.float32)
    return g + lut[idx]
