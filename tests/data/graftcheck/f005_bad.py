"""F005 fixture: a request-path method blocks on ``result()`` with no
timeout — an unhealthy dependency now wedges the caller's thread
instead of degrading the one request."""


class Client:
    def __init__(self, pool):
        self._pool = pool

    def fetch(self, query):
        fut = self._pool.submit(query)
        return fut.result()  # the finding: unbudgeted block
