"""K002 clean twin: the same blocked kernel, with its accountant."""

import jax
from jax.experimental import pallas as pl


def doubled_vmem_bytes(tile_rows: int) -> int:
    # in block + out block, fp32
    return 2 * tile_rows * 128 * 4


def _double_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2.0


def doubled(x):
    return pl.pallas_call(
        _double_kernel,
        grid=(8,),
        in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
    )(x)
