"""T004 clean twin: the wait re-checks its predicate in a while loop,
so spurious/stolen wakeups just go back to sleep."""
import threading


class Gate:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self.ready = False  # guarded_by: _lock

    def await_ready(self):
        with self._cv:
            while not self.ready:
                self._cv.wait()

    def open(self):
        with self._cv:
            self.ready = True
            self._cv.notify_all()
