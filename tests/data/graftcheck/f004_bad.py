"""F004 fixture: ``__init__`` stores a worker thread on ``self`` but no
``join`` is reachable from stop/close/__exit__ — stop() flips a flag
and forgets the thread, so shutdown leaks it."""

import threading


class Pump:
    def __init__(self):
        self._stop = threading.Event()
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    def _run(self):
        while not self._stop.wait(0.05):
            pass

    def stop(self):
        self._stop.set()  # the finding: self._worker is never joined
