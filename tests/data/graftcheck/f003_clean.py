"""F003 clean twin: every handler accounts for the failure — records a
metric, captures it into state, re-raises — and the best-effort
teardown idiom (``try: sock.close() / except OSError: pass``) is
exempt because silence IS the correct accounting for a socket that is
already dying."""


def drain(batch, errors_total, log):
    done = 0
    last_error = None
    for job in batch:
        try:
            job.run()
            done += 1
        except TimeoutError:
            errors_total.inc()
        except ValueError as e:
            last_error = e
        except Exception:
            log.exception("job failed")
    if last_error is not None:
        raise last_error
    return done


def reroute(job, primary, fallback):
    try:
        return primary.run(job)
    except ConnectionError:
        return fallback.run(job)  # the return IS the handling


def hangup(sock):
    try:
        sock.close()
    except OSError:
        pass  # best-effort teardown: the peer is already gone
