"""T001 clean twin: the same two-line read-modify-write, but guarded
(and the guard declared) — exact under any interleaving."""
import threading


class SharedCounter:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0  # guarded_by: _lock

    def add(self, n):
        for _ in range(n):
            with self._lock:
                v = self.count
                self.count = v + 1

    def spin(self, n):
        t = threading.Thread(target=self.add, args=(n,))
        t.start()
        return t
