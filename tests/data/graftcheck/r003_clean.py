"""R003 clean twin: one wrapper, static argument varies per call."""
import functools

import jax


@functools.partial(jax.jit, static_argnames=("s",))
def scaled(v, s):
    return v * s


def compiles_once_per_scale(xs):
    return [scaled(xs, s) for s in (1, 2, 3)]
