"""R002 fixture: Python `if` on a traced value inside jit."""
import jax
import jax.numpy as jnp


@jax.jit
def branches_on_tracer(x):
    s = jnp.sum(x)
    if s:  # TracerBoolConversionError at trace time
        return x
    return -x
