"""F001 fixture: the module declares a typed failure hierarchy in its
``__all__`` but one raise site reaches for a bare ``RuntimeError`` —
callers that classify failures by isinstance cannot route it."""

__all__ = ["ShardError"]


class ShardError(Exception):
    pass


def lookup(table, shard):
    if shard not in table:
        raise RuntimeError(f"no shard {shard}")  # untyped: the finding
    return table[shard]
