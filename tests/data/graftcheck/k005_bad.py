"""K005 bad twin: the loop body returns one more carry element than
the init tuple provides."""

import jax
from jax.experimental import pallas as pl  # noqa: F401


def scan_rows(x):
    def body(i, carry):
        acc, best = carry
        return (acc + x[i], best, i)

    return jax.lax.fori_loop(0, 4, body, (0.0, 0.0))
