"""F002 clean twin: every CFG path — including the exception edge —
settles the owned future exactly once, and the double-settle race is
fenced with an InvalidStateError once-guard."""


def finish(fut, compute):
    try:
        fut.set_result(compute())
    except Exception as e:
        fut.set_exception(e)


def finish_racy(fut, outcome):
    # a late completion may race a deadline settle: second set loses
    try:
        fut.set_result(outcome)
    except InvalidStateError:
        pass


def delegate(pool, query):
    fut = pool.submit(query)
    return fut  # visible hand-off: the caller now owns settlement
