"""T004 fixture: Condition.wait under `if` instead of `while` — a
spurious wakeup (or a stolen wakeup between notify and resume) proceeds
with the predicate still false."""
import threading


class Gate:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self.ready = False  # guarded_by: _lock

    def await_ready(self):
        with self._cv:
            if not self.ready:
                self._cv.wait()

    def open(self):
        with self._cv:
            self.ready = True
            self._cv.notify_all()
