"""R004 fixture: cross-package import of a private name."""
from raft_tpu.fixture_pkg_a.r004_provider import _detail_kernel


def consumes_detail(x):
    return _detail_kernel(x)
