"""F003 fixture: the except body swallows the failure — no re-raise, no
settle, no metric/span/log, no capture — so a shed request simply
vanishes from the accounting."""


def drain(batch):
    done = 0
    for job in batch:
        try:
            job.run()
            done += 1
        except Exception:
            pass  # the finding: failure leaves no trace anywhere
    return done
