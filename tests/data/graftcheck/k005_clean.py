"""K005 clean twin: carry arity matches across both loop forms."""

import jax
from jax.experimental import pallas as pl  # noqa: F401


def scan_rows(x):
    def body(i, carry):
        acc, best = carry
        return (acc + x[i], best)

    return jax.lax.fori_loop(0, 4, body, (0.0, 0.0))


def drain(x):
    return jax.lax.while_loop(
        lambda carry: carry[0] < 8,
        lambda carry: (carry[0] + 1, carry[1] * 2),
        (0, x),
    )
