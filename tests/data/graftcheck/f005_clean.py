"""F005 clean twin: request-path blocking derives its budget from the
caller's deadline, and the bare wait on the background worker thread is
exempt — an idle park on a non-request thread is not a request stall."""

import threading
import time


class Client:
    def __init__(self, pool):
        self._pool = pool

    def fetch(self, query, deadline_s):
        fut = self._pool.submit(query)
        remaining = deadline_s - time.monotonic()
        return fut.result(timeout=remaining)


class Worker:
    def __init__(self):
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while not self._stop.is_set():
            self._wake.wait()  # background idle park: exempt
            self._wake.clear()

    def stop(self):
        self._stop.set()
        self._wake.set()
        self._thread.join()
