"""R003 fixture: jax.jit constructed inside a loop."""
import jax


def compiles_every_iteration(xs):
    out = []
    for scale in (1, 2, 3):
        f = jax.jit(lambda v, s=scale: v * s)
        out.append(f(xs))
    return out
