"""R006 fixture: undecorated public entry points in a neighbors module
(analysed under modname ``raft_tpu.neighbors.r006_bad``)."""

import jax.numpy as jnp

from raft_tpu.core import tracing


def build(dataset):
    # flagged: public build entry point with no tracing scope
    return jnp.asarray(dataset)


def search(index, queries, k):
    # flagged: the decorator is missing even though tracing is imported
    del tracing
    return jnp.asarray(queries)[:k]


def _private_search(index, queries, k):
    # not flagged: private helper, not an entry point
    return jnp.asarray(queries)[:k]


def extend(index, vectors):
    # not flagged: `extend` is not in the required-name set
    return index
