"""R004 fixture provider: a package-private detail plus its public name."""


def _detail_kernel(x):
    return x * 2


public_kernel = _detail_kernel
