"""R006 fixture: every public entry point carries a tracing scope
(analysed under modname ``raft_tpu.neighbors.r006_clean``)."""

import jax.numpy as jnp

from raft_tpu.core import tracing
from raft_tpu.core.tracing import annotate


@tracing.range("fixture.build")
def build(dataset):
    return jnp.asarray(dataset)


@annotate("fixture.search")
def search(index, queries, k):
    # `annotate` also satisfies the rule (named_scope without the
    # profiler annotation)
    return jnp.asarray(queries)[:k]


def knn(queries, dataset, k):  # graftcheck: R006 (wrapper delegates)
    return search(build(dataset), queries, k)
