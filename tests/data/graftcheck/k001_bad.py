"""K001 bad twin: async copy started, .wait() only on one branch."""

from jax.experimental import pallas as pl  # noqa: F401
from jax.experimental.pallas import tpu as pltpu


def leaky_kernel(src_ref, dst_ref, sem, flag):
    cp = pltpu.make_async_copy(src_ref, dst_ref, sem)
    cp.start()
    if flag:
        cp.wait()
    dst_ref[0, 0] = dst_ref[0, 0] + 1.0
