"""F001 clean twin: every raise resolves into the module's exported
typed hierarchy (directly, or via the class-hierarchy index for
non-exported subclasses), uses the TypeError/ValueError/AssertionError
validation whitelist, or is a bare re-raise."""

__all__ = ["ShardError", "ShardTimeout"]


class ShardError(Exception):
    pass


class ShardTimeout(ShardError):
    pass


class _Internal(ShardError):
    # not exported, but resolves to ShardError through the hierarchy
    pass


def lookup(table, shard):
    if not isinstance(shard, int):
        raise TypeError("shard must be an int")  # validation whitelist
    try:
        return table[shard]
    except KeyError:
        raise ShardTimeout(f"no shard {shard}") from None


def probe(table, shard):
    try:
        return lookup(table, shard)
    except ShardTimeout:
        raise  # bare re-raise keeps the original type
    except ShardError as e:
        raise _Internal(str(e))
