"""T003 fixture: an unbounded Future.result() while holding the lock —
every other method on the object stalls behind a result that may never
come."""
import threading
from concurrent.futures import ThreadPoolExecutor


class Collector:
    def __init__(self):
        self._lock = threading.Lock()
        self.results = []  # guarded_by: _lock
        self._pool = ThreadPoolExecutor(max_workers=1)

    def run(self, fn):
        fut = self._pool.submit(fn)
        with self._lock:
            self.results.append(fut.result())
