"""K003 bad twin: the output block's index map ignores grid axis 1
(the block stays VMEM-resident across it) but the kernel accumulates
without a first-visit init."""

import jax
from jax.experimental import pallas as pl


def _acc_kernel(x_ref, o_ref):
    o_ref[...] = o_ref[...] + x_ref[...]


def reduce_cols(x):
    return pl.pallas_call(
        _acc_kernel,
        grid=(4, 4),
        in_specs=[pl.BlockSpec((8, 128), lambda i, j: (i, j))],
        out_specs=pl.BlockSpec((8, 128), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((32, 128), x.dtype),
    )(x)
