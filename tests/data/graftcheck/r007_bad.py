"""R007 fixture: dispatch decision with no execution-plan attribution
(analysed under modname ``raft_tpu.neighbors.r007_bad``)."""

import jax.numpy as jnp

from raft_tpu.ops import pallas_kernels as pk


def silently_falls_back(queries, k, scan_mode="auto"):
    # flagged: consults fused_dispatch, then the losing branch runs with
    # no record_dispatch anywhere in the function — the exact silent
    # XLA fallback the explain layer exists to make visible
    use_fused, interpret = pk.fused_dispatch("brute_force", scan_mode)
    if use_fused:
        return jnp.zeros((queries.shape[0], k))
    return jnp.ones((queries.shape[0], k))


def _helper_without_dispatch(queries, k):
    # not flagged: no dispatch decision here
    return jnp.zeros((queries.shape[0], k))
