"""K001 clean twin: every start paired on every path, semaphores
balanced, plus the legal descriptor-wait and loop-body idioms."""

import jax
from jax.experimental import pallas as pl  # noqa: F401
from jax.experimental.pallas import tpu as pltpu


def paired_kernel(src_ref, dst_ref, sem, flag):
    cp = pltpu.make_async_copy(src_ref, dst_ref, sem)
    cp.start()
    if flag:
        dst_ref[0, 0] = 0.0
    cp.wait()


def loop_kernel(src_ref, dst_ref, sem, n):
    def body(i, carry):
        cp = pltpu.make_async_copy(src_ref.at[i], dst_ref.at[i], sem)
        cp.start()
        cp.wait()
        return carry

    return jax.lax.fori_loop(0, n, body, 0)


def await_elsewhere(src_ref, dst_ref, sem):
    # the copy was started by a neighbor device; waiting on a fresh
    # descriptor for the same (src, dst, sem) triple is the idiom
    pltpu.make_async_copy(src_ref, dst_ref, sem).wait()


def barrier_kernel(left, right):
    bar = pltpu.get_barrier_semaphore()
    pltpu.semaphore_signal(bar, device_id=left)
    pltpu.semaphore_signal(bar, device_id=right)
    pltpu.semaphore_wait(bar, 2)
