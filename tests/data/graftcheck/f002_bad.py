"""F002 fixture: the function owns the future it was handed (it settles
on one branch) but the other branch returns without settling or visibly
handing it off — a caller blocked on ``fut.result()`` hangs forever."""


def finish(fut, outcome):
    if outcome is not None:
        fut.set_result(outcome)
    return outcome  # the no-outcome path leaks fut unsettled
