"""R004 clean twin: crosses the package boundary through the public name."""
from raft_tpu.fixture_pkg_a.r004_provider import public_kernel


def consumes_public(x):
    return public_kernel(x)
