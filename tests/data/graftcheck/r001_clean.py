"""R001 clean twin: the sync happens outside any jit trace."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def stays_on_device(x):
    return jnp.sum(x * x)


def host_wrapper(x):
    return np.asarray(stays_on_device(x))
