"""T003 clean twin: the blocking wait happens outside the lock (and
with a timeout); the lock only covers the shared append."""
import threading
from concurrent.futures import ThreadPoolExecutor


class Collector:
    def __init__(self):
        self._lock = threading.Lock()
        self.results = []  # guarded_by: _lock
        self._pool = ThreadPoolExecutor(max_workers=1)

    def run(self, fn):
        fut = self._pool.submit(fn)
        value = fut.result(timeout=30.0)
        with self._lock:
            self.results.append(value)
