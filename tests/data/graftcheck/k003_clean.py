"""K003 clean twin: same revisited output block, properly initialized
on the first visit of the ignored axis."""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def reduce_vmem_bytes(rows, cols):
    """Live set: one input block + the resident output block."""
    return 2 * rows * cols * 4


def _acc_kernel(x_ref, o_ref):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        o_ref[...] = jnp.zeros_like(o_ref)

    o_ref[...] = o_ref[...] + x_ref[...]


def reduce_cols(x):
    return pl.pallas_call(
        _acc_kernel,
        grid=(4, 4),
        in_specs=[pl.BlockSpec((8, 128), lambda i, j: (i, j))],
        out_specs=pl.BlockSpec((8, 128), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((32, 128), x.dtype),
    )(x)
