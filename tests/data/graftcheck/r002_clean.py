"""R002 clean twin: data-dependent choice via jnp.where; the only Python
branches are on static properties (shape) and `is None`."""
import jax
import jax.numpy as jnp


@jax.jit
def selects_on_device(x, bias=None):
    s = jnp.sum(x)
    if x.shape[0] > 1:
        x = x[:1]
    if bias is not None:
        x = x + bias
    return jnp.where(s > 0, x, -x)
