"""F004 clean twin: every self-held resource is reclaimed from a
stop/close root, including through the alias-swap idiom (``t,
self._t = self._t, None`` then ``t.join()``) that the serving stack
uses to make stop() idempotent."""

import threading


class Pump:
    def __init__(self, interval_s):
        self._stop = threading.Event()
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._timer = threading.Timer(interval_s, self._tick)
        self._worker.start()
        self._timer.start()

    def _run(self):
        while not self._stop.wait(0.05):
            pass

    def _tick(self):
        pass

    def stop(self):
        self._stop.set()
        w, self._worker = self._worker, None
        if w is not None:
            w.join()
        self._timer.cancel()

    def __exit__(self, *exc):
        self.stop()
