"""R007 clean twin: every dispatch decision lands in a reason-coded
ExplainRecord (analysed under modname ``raft_tpu.neighbors.r007_clean``)."""

import jax.numpy as jnp

from raft_tpu.obs import explain as obs_explain
from raft_tpu.ops import pallas_kernels as pk


def attributed_dispatch(queries, k, scan_mode="auto"):
    # clean: both resolved branches record an attribution
    use_fused, interpret, reason = pk.fused_dispatch_explained(
        "brute_force", scan_mode)
    if use_fused:
        obs_explain.record_dispatch("brute_force", scan_mode, "pallas",
                                    reason, params={"k": k})
        return jnp.zeros((queries.shape[0], k))
    obs_explain.record_dispatch("brute_force", scan_mode, "xla", reason,
                                params={"k": k})
    return jnp.ones((queries.shape[0], k))


def attributed_in_closure(queries, k, scan_mode="auto"):
    # clean: the dispatch lives in a nested def; attribution anywhere in
    # the top-level function body satisfies the rule
    def _core(q):
        use_fused, _ = pk.fused_dispatch("brute_force", scan_mode)
        return jnp.zeros((q.shape[0], k)) if use_fused else \
            jnp.ones((q.shape[0], k))

    obs_explain.record_dispatch("brute_force", scan_mode, "xla", "forced")
    return _core(queries)
