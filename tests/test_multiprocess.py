"""True multi-controller (2-process × 4-device) distributed tests.

Reference analog: raft-dask's multi-worker Comms bootstrap + per-worker
builds (raft_dask/common/comms.py:138-173, test_comms.py on a
LocalCUDACluster). Here each process is a jax.distributed controller owning
4 virtual CPU devices; ``init_distributed`` plays the NCCL-uniqueId
rendezvous role and ``build_ivf_pq_from_file`` builds only the shards whose
devices are process-local (per-process row spans of the shared fbin file).
"""

import os
import pathlib
import socket
import subprocess
import sys

import numpy as np
import pytest

_REPO_ROOT = str(pathlib.Path(__file__).resolve().parents[1])

_WORKER = r"""
import os, sys
pid = int(sys.argv[1])
port = sys.argv[2]
fbin_path = sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
sys.path[:] = [p for p in sys.path if ".axon_site" not in p]
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from raft_tpu import Resources, native
from raft_tpu.neighbors import brute_force, ivf_pq
from raft_tpu.parallel import comms as cm, sharded
from raft_tpu.stats import neighborhood_recall

comms = cm.init_distributed(f"localhost:{port}", 2, pid)
assert jax.process_count() == 2
assert comms.size == 8, comms.size

# count the shards this process actually builds (4 of 8)
built = []
orig = sharded._map_shards
def counting_map(c, fn, res, **kw):
    out = orig(c, fn, res, **kw)
    built.extend(out.keys())
    return out
sharded._map_shards = counting_map

idx = sharded.build_ivf_pq_from_file(
    comms, fbin_path,
    ivf_pq.IndexParams(n_lists=4, pq_dim=8, kmeans_n_iters=3),
    res=Resources(seed=2), batch_rows=400, scan_mode="lut")
print(f"P{pid} LOCAL_BUILDS {sorted(built)}", flush=True)

db = native.read_bin(fbin_path)
rng = np.random.default_rng(11)
q = rng.standard_normal((20, db.shape[1])).astype(np.float32)
d, i = sharded.search_ivf_pq(idx, q, 10, ivf_pq.SearchParams(n_probes=4))
i = np.asarray(i)
assert i.shape == (20, 10)
assert (i >= -1).all() and (i < len(db)).all()
_, gt = brute_force.knn(q, db, k=10, metric="sqeuclidean")
rec = float(neighborhood_recall(i, np.asarray(gt)))
print(f"P{pid} RECALL {rec:.4f}", flush=True)
assert rec >= 0.6, rec
print(f"P{pid} OK", flush=True)
"""


def _free_port():
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_two_process_distributed_build_and_search(tmp_path):
    from raft_tpu import native

    rng = np.random.default_rng(7)
    db = rng.standard_normal((1600, 16)).astype(np.float32)
    fbin = str(tmp_path / "base.fbin")
    native.write_bin(fbin, db)
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER)
    port = _free_port()

    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    env["PYTHONPATH"] = _REPO_ROOT + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(pid), str(port), fbin],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=_REPO_ROOT)
        for pid in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=900)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {pid} failed:\n{out[-4000:]}"
        assert f"P{pid} OK" in out, out[-4000:]
    joined = "\n".join(outs)
    # each controller built exactly its 4 local shards
    assert "P0 LOCAL_BUILDS [0, 1, 2, 3]" in joined, joined[-4000:]
    assert "P1 LOCAL_BUILDS [4, 5, 6, 7]" in joined, joined[-4000:]
