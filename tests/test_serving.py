"""Deterministic CPU tests for the async micro-batching serving engine.

Covers the batcher flush policy (fake clock, no threads), bucket-padding
exactness (engine rows bit-identical to solo searches), concurrent
submitters, drain/shutdown with in-flight requests, fake-clock stats
accuracy, and the warm-start guarantee (first submit compiles nothing,
via the jax.monitoring compile hook)."""

import threading
import time
from concurrent.futures import Future

import numpy as np
import pytest

from raft_tpu import serving
from raft_tpu.serving.batcher import Batcher, EngineStopped, QueueFull, Request
from raft_tpu.serving.engine import _default_warm_buckets, compile_count
from raft_tpu.serving.stats import ServingStats, percentiles

pytestmark = pytest.mark.fast

DIM = 16
K = 5


def _req(k=10, t=0.0, query=None):
    return Request(query if query is not None
                   else np.zeros(DIM, np.float32), k, Future(), t)


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


# ---------------------------------------------------------------- batcher
def test_max_batch_flush_ignores_deadline():
    clock = FakeClock()
    b = Batcher(max_batch=4, max_wait_us=10_000_000, clock=clock)
    for _ in range(5):
        b.put(_req(t=clock.t))
    with b.locked():
        batch = b.select(clock())  # t=0: deadline nowhere near
    assert batch is not None and len(batch) == 4
    assert len(b) == 1  # the fifth stays queued


def test_deadline_flush_of_partial_batch():
    clock = FakeClock()
    b = Batcher(max_batch=8, max_wait_us=1000, clock=clock)
    for _ in range(3):
        b.put(_req(t=clock.t))
    with b.locked():
        assert b.select(clock()) is None          # deadline not reached
    clock.t = 0.0009
    with b.locked():
        assert b.select(clock()) is None          # 0.9 ms < 1 ms
    clock.t = 0.0011
    with b.locked():
        batch = b.select(clock())                 # oldest aged out
    assert batch is not None and len(batch) == 3
    assert len(b) == 0


def test_distinct_k_never_coalesces_and_fifo_across_groups():
    clock = FakeClock()
    b = Batcher(max_batch=8, max_wait_us=0, clock=clock)
    b.put(_req(k=10, t=0.0))
    b.put(_req(k=5, t=0.0))
    b.put(_req(k=10, t=0.0))
    with b.locked():
        first = b.select(clock())
    assert [r.k for r in first] == [10, 10]  # same-k group, FIFO head
    with b.locked():
        second = b.select(clock())
    assert [r.k for r in second] == [5]


def test_queue_limit_backpressure():
    b = Batcher(max_batch=8, max_wait_us=0, queue_limit=2)
    b.put(_req())
    b.put(_req())
    with pytest.raises(QueueFull):
        b.put(_req(), block=False)
    with pytest.raises(QueueFull):
        b.put(_req(), block=True, timeout=0.01)


def test_stop_drain_voids_deadline_and_no_drain_returns_cancelled():
    clock = FakeClock()
    b = Batcher(max_batch=8, max_wait_us=10_000_000, clock=clock)
    b.put(_req(t=0.0))
    assert b.stop(drain=True) == []
    with b.locked():
        batch = b.select(clock())  # stopping: flush immediately
    assert batch is not None and len(batch) == 1
    assert b.take(block=True) is None  # drained + stopping -> None

    b2 = Batcher(max_batch=8, max_wait_us=10_000_000, clock=clock)
    r = _req(t=0.0)
    b2.put(r)
    cancelled = b2.stop(drain=False)
    assert cancelled == [r]
    with pytest.raises(EngineStopped):
        b2.put(_req())


def test_default_warm_buckets_cover_every_batch_size():
    from raft_tpu.utils.shape import query_bucket

    for max_batch in (1, 7, 8, 64, 256):
        buckets = _default_warm_buckets(max_batch)
        reachable = {query_bucket(n) for n in range(1, max_batch + 1)}
        assert set(buckets) == reachable


# ------------------------------------------------------------------ stats
def test_percentiles_nearest_rank_exact():
    samples = list(range(1, 101))  # 1..100
    p = percentiles(samples)
    assert p == {"p50": 50, "p95": 95, "p99": 99}
    assert percentiles([7.0]) == {"p50": 7.0, "p95": 7.0, "p99": 7.0}


def test_stats_counters_and_latency_under_fake_clock():
    st = ServingStats()
    st.record_submit(4)
    # batch of 3 launched at t=1.0, submitted at t=0.2/0.5/0.9,
    # results on host at t=1.5
    waits = [1.0 - 0.2, 1.0 - 0.5, 1.0 - 0.9]
    totals = [1.5 - 0.2, 1.5 - 0.5, 1.5 - 0.9]
    st.record_batch(3, 8, waits, 0.5, totals)
    st.record_batch(1, 8, [0.0], 0.25, [0.25])
    st.record_cancelled()
    snap = st.snapshot()
    assert snap["n_submitted"] == 4
    assert snap["n_completed"] == 4
    assert snap["n_cancelled"] == 1
    assert snap["n_batches"] == 2
    assert snap["batch_size_hist"] == {1: 1, 3: 1}
    assert snap["bucket_hist"] == {8: 2}
    assert snap["mean_batch_size"] == 2.0
    # percentiles are histogram-bucket interpolated now (exact to within
    # one exponential bucket of DEFAULT_LATENCY_BUCKETS); the queue
    # waits are [800, 500, 100, 0] ms, so p50 (rank 2) lands in the
    # (51.2, 102.4] ms bucket and p99 in (409.6, 819.2] ms
    assert 51.2 <= snap["queue_wait_ms"]["p50"] <= 102.4
    assert 409.6 <= snap["queue_wait_ms"]["p99"] <= 819.2
    # totals [1300, 1000, 600, 250] ms: p50 in (409.6, 819.2] ms
    assert 409.6 <= snap["total_ms"]["p50"] <= 819.2
    # means are exact, not bucketed
    assert snap["queue_wait_ms"]["mean"] == pytest.approx(350.0)
    assert snap["device_ms"]["mean"] == pytest.approx(437.5)
    st.reset_samples()
    snap2 = st.snapshot()
    assert "total_ms" not in snap2 and snap2["n_completed"] == 4


# ----------------------------------------------------------------- engine
@pytest.fixture(scope="module")
def flat_searcher():
    from raft_tpu.neighbors import ivf_flat

    rng = np.random.default_rng(3)
    db = rng.standard_normal((1500, DIM)).astype(np.float32)
    index = ivf_flat.build(db, ivf_flat.IndexParams(n_lists=16))
    return serving.ivf_flat_searcher(index,
                                     ivf_flat.SearchParams(n_probes=8))


def _engine(searcher, **kw):
    kw.setdefault("max_batch", 8)
    kw.setdefault("max_wait_us", 5000)
    kw.setdefault("warm_ks", (K,))
    return serving.Engine(searcher, serving.EngineConfig(**kw))


def test_warm_start_first_submit_compiles_nothing(flat_searcher):
    rng = np.random.default_rng(0)
    with _engine(flat_searcher) as eng:
        assert eng.warmup_info["compiles"] >= 0  # hook live from start()
        c0 = compile_count()
        futs = [eng.submit(rng.standard_normal(DIM, np.float32)
                           .astype(np.float32), K) for _ in range(17)]
        for f in futs:
            d, i = f.result(timeout=60)
            assert d.shape == (K,) and i.shape == (K,)
        assert compile_count() - c0 == 0, (
            "serving path compiled after Engine.start() warmup")


def test_coalesced_results_bit_identical_to_solo(flat_searcher):
    rng = np.random.default_rng(1)
    queries = [rng.standard_normal(DIM).astype(np.float32)
               for _ in range(12)]
    with _engine(flat_searcher, max_wait_us=50_000) as eng:
        futs = [eng.submit(q, K) for q in queries]
        results = [f.result(timeout=60) for f in futs]
        placements = [f.placement for f in futs]
    # vs the solo oracle at the same bucket/row (all four families obey)
    assert serving.verify_bit_identity(
        flat_searcher, queries, results, K, placements) == 0
    # stronger, row-position-free claim for the row-independent families:
    # the engine row equals a plain solo search() of just that query
    # whenever the coalesced bucket matches the solo bucket
    for q, (d_row, i_row), (_, bucket) in zip(queries, results, placements):
        if bucket == 8:  # query_bucket(1) == 8: same compiled program
            d_solo, i_solo = flat_searcher.search(q[None], K)
            np.testing.assert_array_equal(i_row, np.asarray(i_solo)[0])
            np.testing.assert_array_equal(d_row, np.asarray(d_solo)[0])


def test_concurrent_submitters_all_complete_and_match(flat_searcher):
    rng = np.random.default_rng(2)
    n_threads, per_thread = 6, 8
    queries = [[rng.standard_normal(DIM).astype(np.float32)
                for _ in range(per_thread)] for _ in range(n_threads)]
    out = [[None] * per_thread for _ in range(n_threads)]
    placements = [[None] * per_thread for _ in range(n_threads)]
    with _engine(flat_searcher, max_wait_us=2000) as eng:
        def worker(ti):
            for j, q in enumerate(queries[ti]):
                f = eng.submit(q, K)
                out[ti][j] = f.result(timeout=60)
                placements[ti][j] = f.placement

        threads = [threading.Thread(target=worker, args=(ti,))
                   for ti in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        snap = eng.stats.snapshot()
    total = n_threads * per_thread
    assert snap["n_submitted"] == total
    assert snap["n_completed"] == total
    assert sum(b * c for b, c in snap["batch_size_hist"].items()) == total
    flat_q = [q for qs in queries for q in qs]
    flat_r = [r for rs in out for r in rs]
    flat_p = [p for ps in placements for p in ps]
    assert serving.verify_bit_identity(
        flat_searcher, flat_q, flat_r, K, flat_p) == 0


def test_stop_with_drain_completes_in_flight(flat_searcher):
    rng = np.random.default_rng(4)
    # a deadline far in the future: requests are still queued when stop()
    # lands, so drain must flush them
    eng = _engine(flat_searcher, max_wait_us=30_000_000, max_batch=64)
    eng.start()
    futs = [eng.submit(rng.standard_normal(DIM).astype(np.float32), K)
            for _ in range(5)]
    assert not any(f.done() for f in futs[:1])  # deadline not reachable
    eng.stop(drain=True)
    for f in futs:
        d, i = f.result(timeout=10)  # resolved by the drain flush
        assert i.shape == (K,)
    with pytest.raises(EngineStopped):
        eng.submit(np.zeros(DIM, np.float32), K)


def test_stop_without_drain_fails_queued_requests(flat_searcher):
    eng = _engine(flat_searcher, max_wait_us=30_000_000, max_batch=64)
    eng.start()
    futs = [eng.submit(np.zeros(DIM, np.float32), K) for _ in range(3)]
    eng.stop(drain=False)
    for f in futs:
        assert f.cancelled() or isinstance(f.exception(), EngineStopped)
    snap = eng.stats.snapshot()
    assert snap["n_cancelled"] == 3


def test_drain_waits_for_outstanding(flat_searcher):
    rng = np.random.default_rng(5)
    with _engine(flat_searcher, max_wait_us=1000) as eng:
        futs = [eng.submit(rng.standard_normal(DIM).astype(np.float32), K)
                for _ in range(9)]
        assert eng.drain(timeout=60)
        assert all(f.done() for f in futs)


def test_submit_validation_and_distinct_k(flat_searcher):
    with _engine(flat_searcher, max_wait_us=0) as eng:
        with pytest.raises(ValueError):
            eng.submit(np.zeros(DIM + 1, np.float32), K)
        d5, i5 = eng.submit(np.zeros(DIM, np.float32), K).result(60)
        d3, i3 = eng.submit(np.zeros(DIM, np.float32), 3).result(60)
        assert i5.shape == (K,) and i3.shape == (3,)


@pytest.mark.slow
def test_open_loop_soak(flat_searcher):
    """Open-loop Poisson soak: sustained arrivals, no deadlock, stats
    account for every request (the serving_bench open-loop mode in
    miniature)."""
    rng = np.random.default_rng(6)
    n = 150
    with _engine(flat_searcher, max_wait_us=2000) as eng:
        futs = []
        for gap in rng.exponential(1 / 200.0, n):
            time.sleep(gap)
            futs.append(eng.submit(
                rng.standard_normal(DIM).astype(np.float32), K))
        for f in futs:
            f.result(timeout=60)
        snap = eng.stats.snapshot()
    assert snap["n_completed"] == n
    assert snap["total_ms"]["p50"] > 0
    assert sum(snap["bucket_hist"].values()) == snap["n_batches"]


# --------------------------------------- deadlines (fake clock + live)
def test_batcher_prunes_expired_before_selection():
    """select() sheds deadline-blown requests BEFORE picking a batch —
    they never launch, and pop_expired() hands them to the engine."""
    clock = FakeClock()
    b = Batcher(max_batch=4, max_wait_us=10_000_000, clock=clock)
    doomed = Request(np.zeros(DIM, np.float32), 10, Future(), 0.0,
                     t_deadline=0.5)
    patient = _req(t=0.0)  # no deadline: only the 10 s flush applies
    b.put(doomed)
    b.put(patient)

    clock.t = 0.3
    with b.locked():
        assert b.select(clock()) is None  # nothing due, nothing expired
    assert b.pop_expired() == []

    clock.t = 0.6
    with b.locked():
        assert b.select(clock()) is None  # doomed pruned, patient waits
    assert b.pop_expired() == [doomed]
    assert len(b) == 1

    clock.t = 10.1
    with b.locked():
        assert b.select(clock()) == [patient]  # flush deadline reached


def test_take_wakes_at_shed_deadline_not_flush_deadline():
    """A queued request's deadline_ms bounds how long take() sleeps: the
    shed must fire at ~deadline, not at the (much later) flush wait."""
    b = Batcher(max_batch=8, max_wait_us=30_000_000)
    b.put(Request(np.zeros(DIM, np.float32), 10, Future(),
                  time.perf_counter(),
                  t_deadline=time.perf_counter() + 0.05))
    t0 = time.perf_counter()
    got = b.take(block=True)  # [] = "expired pending", wakes the engine
    assert got == []
    assert time.perf_counter() - t0 < 5.0
    assert len(b.pop_expired()) == 1


def test_search_end_to_end_deadline(flat_searcher):
    """Engine.search(deadline_ms=...) is ONE budget across admission,
    queueing, and device time — unlike submit(timeout=), which bounds
    only admission (docs/serving.md). A launched-but-slow batch raises
    the same typed DeadlineExceeded instead of blocking past it."""
    from raft_tpu.serving import DeadlineExceeded
    from raft_tpu.testing import faults

    with _engine(flat_searcher) as eng:
        # sanity: generous deadline -> normal rows
        d, i = eng.search(np.zeros(DIM, np.float32), K, deadline_ms=30_000)
        assert d.shape == (K,)
        with faults.slow_searcher(flat_searcher, 1.0):
            t0 = time.perf_counter()
            with pytest.raises(DeadlineExceeded):
                eng.search(np.zeros(DIM, np.float32), K, deadline_ms=200)
            # returned at the deadline, not after the 1 s device stall
            assert time.perf_counter() - t0 < 0.9
