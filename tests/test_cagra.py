"""CAGRA tests — build→optimize→search with recall gates against exact
ground truth (reference pattern: cpp/test/neighbors/ann_cagra.cuh, min_recall
floors ~0.69+ for low-itopk configs; we gate higher on small data)."""

import io

import numpy as np
import pytest

from raft_tpu.neighbors import brute_force, cagra
from raft_tpu.stats import neighborhood_recall


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(7)
    db = rng.standard_normal((3000, 32)).astype(np.float32)
    q = rng.standard_normal((100, 32)).astype(np.float32)
    return db, q


@pytest.fixture(scope="module")
def gt(data):
    db, q = data
    _, idx = brute_force.knn(q, db, k=10, metric="sqeuclidean")
    return np.asarray(idx)


@pytest.fixture(scope="module")
def built(data):
    db, _ = data
    params = cagra.IndexParams(
        intermediate_graph_degree=48, graph_degree=24,
        build_algo=cagra.BuildAlgo.NN_DESCENT, nn_descent_niter=12)
    return cagra.build(db, params)


def test_build_shapes(built, data):
    db, _ = data
    assert built.graph.shape == (len(db), 24)
    g = np.asarray(built.graph)
    assert (g >= 0).all() and (g < len(db)).all()
    assert not (g == np.arange(len(db))[:, None]).any()


def test_graph_has_no_duplicate_edges(built):
    g = np.asarray(built.graph)
    for row in g[:100]:
        assert len(np.unique(row)) == len(row)


def test_search_recall(built, data, gt):
    _, q = data
    d, i = cagra.search(built, q, 10,
                        cagra.SearchParams(itopk_size=64, search_width=2))
    recall = float(neighborhood_recall(np.asarray(i), gt))
    assert recall >= 0.9, f"recall {recall}"


def test_search_recall_increases_with_itopk(built, data, gt):
    _, q = data
    r = []
    for itopk in (16, 64):
        _, i = cagra.search(built, q, 10, cagra.SearchParams(itopk_size=itopk))
        r.append(float(neighborhood_recall(np.asarray(i), gt)))
    assert r[1] >= r[0] - 0.02
    assert r[1] >= 0.85


def test_search_distances_match_exact(built, data):
    db, q = data
    d, i = cagra.search(built, q, 5,
                        cagra.SearchParams(itopk_size=64, search_width=2))
    d, i = np.asarray(d), np.asarray(i)
    # returned distances must equal the true L2² to the returned ids
    want = ((q[:, None, :] - db[i]) ** 2).sum(-1)
    np.testing.assert_allclose(d, want, rtol=1e-3, atol=1e-3)


@pytest.mark.slow
def test_random_samplings_recover_disconnected_clusters():
    """num_random_samplings scales the seed pool: with well-separated
    clusters the kNN graph is disconnected, so recall is seed-bound —
    more random seeds must recover it (reference lever:
    search_params.num_random_samplings, cagra_types.hpp:66-116)."""
    from raft_tpu.bench.datagen import low_rank_clusters

    rng = np.random.default_rng(31)
    n = 8000
    # spread=4: deliberately disconnected clusters (the seeding stress)
    both = low_rank_clusters(rng, n + 300, 64, n_centers=64, intrinsic=8,
                             spread=4.0)
    db, q = both[:n], both[n:]
    _, gt = brute_force.knn(q, db, k=10, metric="sqeuclidean")
    gt = np.asarray(gt)
    idx = cagra.build(db, cagra.IndexParams(
        intermediate_graph_degree=48, graph_degree=24))
    recalls = {}
    for nr in (1, 8):
        _, i = cagra.search(idx, q, 10, cagra.SearchParams(
            itopk_size=64, search_width=2, num_random_samplings=nr))
        recalls[nr] = float(neighborhood_recall(np.asarray(i), gt))
    assert recalls[8] >= 0.97, recalls
    assert recalls[8] >= recalls[1] - 1e-6, recalls


def _naive_detour_counts(g):
    """Direct transcription of the detour-count definition (the oracle the
    blocked kernel must match bit-for-bit)."""
    n, k = g.shape
    out = np.zeros((n, k), np.int32)
    for i in range(n):
        for a in range(k):
            if g[i, a] < 0:
                continue
            for b in range(a):
                if g[i, b] >= 0 and g[i, a] in g[g[i, b]]:
                    out[i, a] += 1
    return out


def test_detour_counts_match_naive_oracle():
    import jax.numpy as jnp

    from raft_tpu.neighbors.cagra import _detour_counts_jit

    rng = np.random.default_rng(11)
    # unique-id rows with some -1 padded tails
    g = np.stack([rng.choice(80, 14, replace=False)
                  for _ in range(80)]).astype(np.int32)
    g[3, 10:] = -1
    g[20, 5:] = -1
    got = np.asarray(_detour_counts_jit(jnp.asarray(g), 16))
    np.testing.assert_array_equal(got, _naive_detour_counts(g))
    # duplicate ids: any-over-c semantics, still exact
    g = rng.integers(0, 50, (50, 10)).astype(np.int32)
    got = np.asarray(_detour_counts_jit(jnp.asarray(g), 8))
    np.testing.assert_array_equal(got, _naive_detour_counts(g))


@pytest.mark.slow
def test_optimize_scales_to_wide_graphs():
    """The blocked detour pass must handle CAGRA-flagship graph widths
    (K=128) at 6-figure node counts with bounded memory (VERDICT r1: the
    old [tile,K,K,K] membership tensor could not)."""
    import jax.numpy as jnp

    from raft_tpu.neighbors import cagra as cagra_mod

    rng = np.random.default_rng(5)
    n, k = 120_000, 128
    g = rng.integers(0, n, (n, k)).astype(np.int32)
    out = cagra_mod.optimize(jnp.asarray(g), 64)
    assert out.shape == (n, 64)
    assert (np.asarray(out) >= 0).all()


def test_optimize_standalone(data):
    db, _ = data
    from raft_tpu.neighbors import nn_descent

    nd = nn_descent.build(db, nn_descent.IndexParams(
        graph_degree=32, intermediate_graph_degree=48, max_iterations=8))
    g = cagra.optimize(nd.graph, 16)
    assert g.shape == (len(db), 16)
    gg = np.asarray(g)
    assert (gg >= 0).all()


@pytest.mark.filterwarnings("ignore")
def test_ivf_pq_build_path(data, gt):
    db, q = data
    params = cagra.IndexParams(
        intermediate_graph_degree=32, graph_degree=16,
        build_algo=cagra.BuildAlgo.IVF_PQ)
    index = cagra.build(db, params)
    _, i = cagra.search(index, q, 10,
                        cagra.SearchParams(itopk_size=64, search_width=2))
    assert float(neighborhood_recall(np.asarray(i), gt)) >= 0.8


def test_serialize_roundtrip(built, data, gt):
    _, q = data
    buf = io.BytesIO()
    cagra.serialize(built, buf)
    buf.seek(0)
    index2 = cagra.deserialize(buf)
    d1, i1 = cagra.search(built, q, 10)
    d2, i2 = cagra.search(index2, q, 10)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))


def test_serialize_without_dataset(built, data):
    db, q = data
    buf = io.BytesIO()
    cagra.serialize(built, buf, include_dataset=False)
    buf.seek(0)
    with pytest.raises(ValueError, match="no dataset"):
        cagra.deserialize(buf)
    buf.seek(0)
    index2 = cagra.deserialize(buf, dataset=db)
    _, i1 = cagra.search(built, q, 5)
    _, i2 = cagra.search(index2, q, 5)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))


def test_cagra_filtered_search(rng):
    from raft_tpu.core.bitset import Bitset
    from raft_tpu.neighbors import cagra

    x = rng.standard_normal((500, 16)).astype(np.float32)
    idx = cagra.build(x, cagra.IndexParams(graph_degree=16,
                                           intermediate_graph_degree=32))
    mask = rng.random(500) < 0.7
    bs = Bitset.from_mask(mask)
    q = x[:20] + 0.01 * rng.standard_normal((20, 16)).astype(np.float32)
    d, i = cagra.search(idx, q, 5, cagra.SearchParams(itopk_size=64),
                        filter=bs)
    i = np.asarray(i)
    valid = i >= 0
    assert valid.any()
    assert mask[i[valid]].all()


def test_search_bf16_fast_scan(built, data, gt):
    """bf16 beam-walk gathers + exact fp32 buffer re-rank: recall close to
    the fp32 walk; returned distances exact for the returned ids."""
    db, q = data
    sp = cagra.SearchParams(itopk_size=64, search_width=2,
                            scan_dtype="bfloat16")
    d, i = cagra.search(built, q, 10, sp)
    recall = float(neighborhood_recall(np.asarray(i), gt))
    assert recall >= 0.88, f"bf16 recall {recall}"
    d, i = np.asarray(d), np.asarray(i)
    true = ((q[:, None, :] - db[i]) ** 2).sum(-1)
    np.testing.assert_allclose(d, true, rtol=1e-4, atol=1e-4)
    with pytest.raises(ValueError, match="bfloat16"):
        cagra.search(built, q, 10, cagra.SearchParams(scan_dtype="float16"))
