"""Online recall estimation (docs/observability.md "Online recall").

The contract under test: shadow sampling grades a seeded, per-batch
fraction of completed batches off the hot path; every shed is typed and
counted (never silent); ``kind="shadow_eval"`` spans reconcile 1:1 with
the ``raft_tpu_serving_shadow_total`` accounting and carry the ORIGINAL
request's trace id; and the invariant ``sampled == evaluated + sheds +
error`` holds after drain — including under the chaos injectors.
"""

import collections
import threading
import time

import numpy as np
import pytest

from raft_tpu import serving
from raft_tpu.neighbors import ivf_flat
from raft_tpu.obs import metrics as obm
from raft_tpu.obs.quality import (OnlineRecallEstimator, ShadowSampler,
                                  overlap_at_k)
from raft_tpu.obs.spans import ListSink
from raft_tpu.serving.stats import ServingStats
from raft_tpu.testing import faults

pytestmark = pytest.mark.fast

DIM = 16
K = 5


# ------------------------------------------------------------ overlap@k

def test_overlap_at_k_scoring():
    assert overlap_at_k([1, 2, 3], [3, 2, 1]) == 1.0
    assert overlap_at_k([1, 2, 3], [4, 5, 6]) == 0.0
    # served -1 padding is a recall LOSS: numerator drops it, the
    # denominator stays the oracle's full set
    assert overlap_at_k([1, 2, -1], [1, 2, 3]) == pytest.approx(2 / 3)
    # oracle padding shrinks the denominator (fewer true candidates)
    assert overlap_at_k([1, 9, 9], [1, -1, -1]) == 1.0
    # degenerate oracle: nothing to recall -> vacuous 1.0
    assert overlap_at_k([1, 2], [-1, -1]) == 1.0


# ------------------------------------------------------------ estimator

def test_estimator_windowed_mean_and_gauge():
    reg = obm.Registry()
    est = OnlineRecallEstimator(registry=reg, window=4)
    for r in (0.0, 0.0, 1.0, 1.0, 1.0, 1.0):  # window keeps the last 4
        est.observe("ivf_flat", K, 8, r)
    est.observe("ivf_pq", 10, 16, 0.5)
    assert est.snapshot() == {("ivf_flat", K, 8): (4, 1.0),
                              ("ivf_pq", 10, 16): (1, 0.5)}
    gauge = {k: c.value
             for k, c in reg.get("raft_tpu_online_recall").collect()}
    assert gauge[("ivf_flat", str(K), "8")] == 1.0
    assert gauge[("ivf_pq", "10", "16")] == 0.5


# ----------------------------------------------------- sampler unit tests

def _events():
    """(record_event, Counter) pair for sampler accounting."""
    tally = collections.Counter()

    def record(event, n):
        tally[event] += n

    return record, tally


def _exact_oracle(served):
    """Oracle that agrees with the served ids -> recall 1.0."""
    def oracle(queries, k):
        n = np.asarray(queries).shape[0]
        return np.zeros((n, k)), np.tile(np.asarray(served)[:k], (n, 1))
    return oracle


def _offer_one(sampler, trace_id="t0", ids=(1, 2, 3, 4, 5)):
    q = np.zeros((1, DIM), np.float32)
    return sampler.offer(q, [np.array(ids)], [trace_id], [K],
                         "ivf_flat", 8)


def test_sampler_rate_bounds_and_determinism():
    with pytest.raises(ValueError, match="rate"):
        ShadowSampler(_exact_oracle(range(K)), rate=1.5)
    # the per-batch coin is seeded: same seed + same offer sequence
    # -> identical sampling decisions
    decisions = []
    for _ in range(2):
        s = ShadowSampler(_exact_oracle(range(K)), rate=0.5, seed=7)
        decisions.append([_offer_one(s) for _ in range(32)])
        s.close()
    assert decisions[0] == decisions[1]
    assert any(decisions[0]) and not all(decisions[0])


def test_sampler_grades_and_spans_carry_trace_id():
    record, tally = _events()
    sink = ListSink()
    reg = obm.Registry()
    s = ShadowSampler(_exact_oracle((1, 2, 3, 4, 5)), rate=1.0,
                      record_event=record, span_sink=sink,
                      engine_label="e0", registry=reg)
    assert _offer_one(s, trace_id="trace-a") is True
    s.close()
    assert tally == {"sampled": 1, "evaluated": 1}
    assert s.estimator.snapshot() == {("ivf_flat", K, 8): (1, 1.0)}
    (span,) = sink.records
    assert span["kind"] == "shadow_eval"
    assert span["trace_id"] == "trace-a"  # the ORIGINAL request's id
    assert span["outcome"] == "ok" and span["recall"] == 1.0
    assert span["engine"] == "e0" and span["bucket"] == 8


def test_sampler_rate_zero_and_closed_never_sample():
    record, tally = _events()
    s = ShadowSampler(_exact_oracle(range(K)), rate=0.0,
                      record_event=record)
    assert _offer_one(s) is False
    s.close()
    assert _offer_one(s) is False  # closed sampler declines, no counts
    assert not tally


def test_sampler_sheds_on_full_queue():
    record, tally = _events()
    sink = ListSink()
    entered, release = threading.Event(), threading.Event()

    def slow_oracle(queries, k):
        entered.set()
        release.wait(10)
        n = np.asarray(queries).shape[0]
        return np.zeros((n, k)), np.tile(np.arange(k), (n, 1))

    s = ShadowSampler(slow_oracle, rate=1.0, queue_limit=1,
                      record_event=record, span_sink=sink,
                      registry=obm.Registry())
    _offer_one(s, "t-worker")           # dequeued, wedges the worker
    assert entered.wait(10)
    _offer_one(s, "t-queued")           # occupies the single queue slot
    _offer_one(s, "t-shed")             # full queue: typed shed, hot path
    assert tally["shed_queue"] == 1     # counted synchronously
    release.set()
    s.close()
    assert tally == {"sampled": 3, "evaluated": 2, "shed_queue": 1}
    by_outcome = {r["outcome"]: r["trace_id"] for r in sink.records}
    assert by_outcome["shed_queue"] == "t-shed"


def test_close_with_full_queue_still_stops_worker():
    # graftcheck F002/F003 triage regression: close() used to drop the
    # sentinel when the bounded queue was full, leaving the worker
    # parked on the queue forever — it must evict a sample instead
    record, tally = _events()
    entered, release = threading.Event(), threading.Event()

    def slow_oracle(queries, k):
        entered.set()
        release.wait(10)
        n = np.asarray(queries).shape[0]
        return np.zeros((n, k)), np.tile(np.arange(k), (n, 1))

    s = ShadowSampler(slow_oracle, rate=1.0, queue_limit=1,
                      record_event=record, registry=obm.Registry())
    _offer_one(s, "t-worker")       # dequeued, wedges the worker
    assert entered.wait(10)
    _offer_one(s, "t-queued")       # occupies the single queue slot
    s.close(timeout=0.2)            # full queue: sentinel must still land
    assert tally.get("shed_close") == 1  # the evicted sample is counted
    release.set()
    s._worker.join(10)
    assert not s._worker.is_alive()


def test_sampler_sheds_stale_items_at_deadline():
    record, tally = _events()
    t = [0.0]
    entered, release = threading.Event(), threading.Event()

    def slow_oracle(queries, k):
        if not entered.is_set():
            entered.set()
            release.wait(10)
        n = np.asarray(queries).shape[0]
        return np.zeros((n, k)), np.tile(np.arange(k), (n, 1))

    s = ShadowSampler(slow_oracle, rate=1.0, deadline_ms=250.0,
                      record_event=record, registry=obm.Registry(),
                      clock=lambda: t[0])
    _offer_one(s, "t-worker")   # wedges the worker behind `release`
    assert entered.wait(10)
    _offer_one(s, "t-stale")    # queued at t=0
    t[0] = 1.0                  # 1000 ms later: past the 250 ms deadline
    release.set()
    s.close()
    assert tally == {"sampled": 2, "evaluated": 1, "shed_deadline": 1}


def test_sampler_counts_oracle_errors():
    record, tally = _events()
    sink = ListSink()

    def bad_oracle(queries, k):
        raise RuntimeError("oracle down")

    s = ShadowSampler(bad_oracle, rate=1.0, record_event=record,
                      span_sink=sink, registry=obm.Registry())
    _offer_one(s, "t-err")
    s.close()  # drains: the error is graded before the sentinel lands
    assert tally == {"sampled": 1, "error": 1}
    (span,) = sink.records
    assert span["outcome"] == "error" and "recall" not in span


# ----------------------------------------------- engine integration/chaos

@pytest.fixture(scope="module")
def flat_index():
    rng = np.random.default_rng(3)
    db = rng.standard_normal((1500, DIM)).astype(np.float32)
    return ivf_flat.build(db, ivf_flat.IndexParams(n_lists=16)), db


@pytest.fixture()
def searcher(flat_index):
    idx, _ = flat_index
    return serving.ivf_flat_searcher(idx,
                                     ivf_flat.SearchParams(n_probes=8))


def _np_oracle(db):
    db = np.asarray(db, np.float32)
    db_sq = (db * db).sum(axis=1)

    def oracle(qs, k):
        qs = np.asarray(qs, np.float32)
        d = db_sq[None, :] - 2.0 * (qs @ db.T)
        idx = np.argpartition(d, kth=k - 1, axis=1)[:, :k]
        return np.take_along_axis(d, idx, axis=1), idx

    return oracle


def _engine(s, db, sink=None, **kw):
    kw.setdefault("max_batch", 8)
    kw.setdefault("max_wait_us", 5000)
    kw.setdefault("warm_ks", (K,))
    kw.setdefault("span_sink", sink)
    kw.setdefault("shadow_oracle", _np_oracle(db))
    kw.setdefault("shadow_sample_rate", 1.0)
    kw.setdefault("shadow_deadline_ms", 30_000.0)
    return serving.Engine(s, serving.EngineConfig(**kw))


def _q(rng):
    return rng.standard_normal(DIM).astype(np.float32)


def _reconcile_shadow(sink, stats):
    """The chaos-suite invariant: after drain, sampled == evaluated +
    sheds + error, and shadow_eval spans match the accounting 1:1."""
    sc = stats.shadow_counts
    assert sc["sampled"] == (sc["evaluated"] + sc["shed_queue"]
                             + sc["shed_deadline"] + sc["shed_close"]
                             + sc["error"]), sc
    spans = [r for r in sink.records if r["kind"] == "shadow_eval"]
    tally = collections.Counter(r["outcome"] for r in spans)
    assert tally.get("ok", 0) == sc["evaluated"], (dict(tally), sc)
    assert tally.get("shed_queue", 0) == sc["shed_queue"]
    assert tally.get("shed_deadline", 0) == sc["shed_deadline"]
    assert tally.get("error", 0) == sc["error"]
    return spans, sc


def test_engine_shadow_spans_reconcile_with_counters(searcher, flat_index):
    _, db = flat_index
    rng = np.random.default_rng(0)
    sink = ListSink()
    with _engine(searcher, db, sink, hang_timeout_s=None) as eng:
        futs = [eng.submit(_q(rng), K) for _ in range(12)]
        trace_ids = {f.trace_id for f in futs}
        for f in futs:
            f.result(timeout=60)
        eng.drain(60)
    # stop() closed the sampler: the queue is fully drained
    spans, sc = _reconcile_shadow(sink, eng.stats)
    assert sc["sampled"] == 12  # rate 1.0: every completed request
    # every graded span joins back to a real request's trace id
    assert {s["trace_id"] for s in spans} == trace_ids
    # exact oracle vs n_probes=8 serving: recall lands in the gauge
    (key, (n, mean)), = eng.shadow.estimator.snapshot().items()
    assert key[0] == "ivf_flat" and key[1] == K and n == 12
    assert 0.0 <= mean <= 1.0


def test_engine_shadow_skips_failed_batches(searcher, flat_index):
    _, db = flat_index
    rng = np.random.default_rng(1)
    sink = ListSink()
    with _engine(searcher, db, sink, hang_timeout_s=None) as eng:
        faults.fail_next_dispatch(searcher)
        bad = eng.submit(_q(rng), K)
        with pytest.raises(serving.BatchFailed):
            bad.result(timeout=60)
        oks = [eng.submit(_q(rng), K) for _ in range(6)]
        for f in oks:
            f.result(timeout=60)
        eng.drain(60)
    spans, sc = _reconcile_shadow(sink, eng.stats)
    # only COMPLETED batches are offered: the failed request is never
    # sampled and never graded
    assert sc["sampled"] == 6
    assert bad.trace_id not in {s["trace_id"] for s in spans}


def test_engine_shadow_invariant_holds_after_hang(searcher, flat_index):
    _, db = flat_index
    rng = np.random.default_rng(2)
    sink = ListSink()
    with _engine(searcher, db, sink, hang_timeout_s=1.0,
                 breaker_cooldown_s=0.05) as eng:
        faults.hang_next_dispatch(searcher, hang_s=3.0)
        with pytest.raises(serving.BatchFailed):
            eng.submit(_q(rng), K).result(timeout=60)
        # the engine recovers (breaker half-open probe) and later
        # completions still get sampled and graded
        deadline = 20.0
        ok = 0
        t0 = time.monotonic()
        while ok < 4 and time.monotonic() - t0 < deadline:
            try:
                eng.submit(_q(rng), K).result(timeout=60)
                ok += 1
            except (serving.Overloaded, serving.BatchFailed):
                time.sleep(0.01)
        assert ok == 4
        eng.drain(60)
    _, sc = _reconcile_shadow(sink, eng.stats)
    assert sc["sampled"] == 4  # the hung batch never reached the sampler


def test_batch_spans_carry_explain_briefs_reconciling_with_counter(
        searcher, flat_index):
    """Acceptance: dispatch_total reason labels reconcile 1:1 with the
    request spans' explain breadcrumbs — every served batch carries its
    briefs, their histogram equals the counter delta, and a failed
    dispatch contributes neither (it never reached a family search)."""
    from raft_tpu.obs import explain as obs_explain

    _, db = flat_index
    rng = np.random.default_rng(5)
    sink = ListSink()
    with _engine(searcher, db, sink, hang_timeout_s=None,
                 shadow_sample_rate=0.0) as eng:
        # baseline AFTER start(): warm-up searches dispatch too, but
        # outside any batch, so they must not skew the reconciliation
        before = obs_explain.dispatch_counts()
        faults.fail_next_dispatch(searcher)
        with pytest.raises(serving.BatchFailed):
            eng.submit(_q(rng), K).result(timeout=60)
        for _ in range(9):
            eng.search(_q(rng), K)
        eng.drain(60)
    after = obs_explain.dispatch_counts()

    batches = sink.by_kind("batch")
    ok = [b for b in batches if b["outcome"] == "ok"]
    failed = [b for b in batches if b["outcome"] != "ok"]
    assert failed and all("explain" not in b for b in failed)
    briefs = [e for b in ok for e in b["explain"]]
    assert len(briefs) == len(ok)  # one dispatch per served batch
    tally = collections.Counter(
        (e["family"], e["engine"], e["reason"]) for e in briefs)
    delta = {k: after[k] - before.get(k, 0)
             for k in after if after[k] != before.get(k, 0)}
    assert delta == dict(tally)
    assert all(k[2] != "unknown" for k in delta)


# -------------------------------------------- ServingStats label hygiene

def test_stats_views_isolate_engines_on_a_shared_registry():
    """Two engines sharing one registry must not bleed into each
    other's by-size / by-bucket / shadow views (the PR 6 two-label
    assumption this PR's ``_engine_children`` helper replaced)."""
    reg = obm.Registry()
    a = ServingStats(registry=reg, engine_label="eng-a")
    b = ServingStats(registry=reg, engine_label="eng-b")
    a.record_batch(3, 8, [0.0] * 3, 0.01, [0.01] * 3)
    a.record_batch(1, 8, [0.0], 0.01, [0.01])
    b.record_batch(5, 16, [0.0] * 5, 0.01, [0.01] * 5)
    a.record_shadow("sampled", 4)
    a.record_shadow("evaluated", 3)
    a.record_shadow("shed_queue", 1)
    b.record_shadow("sampled", 1)

    assert a.batch_size_hist == {1: 1, 3: 1}
    assert b.batch_size_hist == {5: 1}
    assert a.bucket_hist == {8: 2}
    assert b.bucket_hist == {16: 1}
    assert a.shadow_counts == {"sampled": 4, "evaluated": 3,
                               "shed_queue": 1, "shed_deadline": 0,
                               "shed_close": 0, "error": 0}
    assert b.shadow_counts["sampled"] == 1
    assert b.shadow_counts["shed_queue"] == 0

    # the snapshot carries the shadow block, and it is per-engine too
    snap = a.snapshot()
    assert snap["shadow"]["sampled"] == 4
    assert snap["batch_size_hist"] == {1: 1, 3: 1}
