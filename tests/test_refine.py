"""Refine tests — exact re-ranking recovers brute-force order from a
candidate superset (reference pattern: cpp/test/neighbors/refine.cu)."""

import numpy as np
import pytest

from raft_tpu.neighbors import brute_force, refine
from raft_tpu.stats import neighborhood_recall


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(3)
    db = rng.standard_normal((2000, 48)).astype(np.float32)
    q = rng.standard_normal((64, 48)).astype(np.float32)
    return db, q


def test_refine_recovers_exact_topk(data):
    db, q = data
    _, cand = brute_force.knn(q, db, k=30, metric="sqeuclidean")
    # shuffle candidates so refine must actually sort
    rng = np.random.default_rng(0)
    cand = np.array(cand)
    for r in cand:
        rng.shuffle(r)
    d, i = refine.refine(db, q, cand, k=10, metric="sqeuclidean")
    gt_d, gt_i = brute_force.knn(q, db, k=10, metric="sqeuclidean")
    assert float(neighborhood_recall(np.asarray(i), np.asarray(gt_i))) >= 0.999
    np.testing.assert_allclose(np.asarray(d), np.asarray(gt_d), rtol=1e-4,
                               atol=1e-4)


def test_refine_handles_missing_candidates(data):
    db, q = data
    _, cand = brute_force.knn(q, db, k=20, metric="sqeuclidean")
    cand = np.asarray(cand).copy()
    cand[:, 15:] = -1  # only 15 real candidates
    d, i = refine.refine(db, q, cand, k=10)
    assert (np.asarray(i) >= 0).all()
    # all returned came from the first 15
    assert np.isin(np.asarray(i), cand[:, :15]).all()


def test_refine_inner_product(data):
    db, q = data
    ip = q @ db.T
    gt = np.argsort(-ip, 1)[:, :5]
    cand = np.argsort(-ip, 1)[:, :25].astype(np.int32)
    rng = np.random.default_rng(1)
    for r in cand:
        rng.shuffle(r)
    d, i = refine.refine(db, q, cand, k=5, metric="inner_product")
    assert float(neighborhood_recall(np.asarray(i), gt)) >= 0.999


def test_refine_validation(data):
    db, q = data
    with pytest.raises(ValueError, match="k="):
        refine.refine(db, q, np.zeros((len(q), 5), np.int32), k=10)
