"""Chaos tests for the multi-replica serving fleet (docs/serving.md
"Fleet").

Each test injects one replica-level failure domain through the real
routing path (the ``raft_tpu.testing.faults`` fleet injectors stop a
real engine / wrap a real handle's ``search``) and pins an invariant
the fleet claims:

- a replica killed mid-batch loses nothing: its riders are retried on
  a sibling and every result stays bit-identical to a solo search on
  whichever replica actually served it;
- a breaker-open replica is routed around, then re-admitted after a
  rate-limited live probe closes the breaker half-open;
- ``rolling_swap`` under concurrent submitters drops zero requests and
  the healthy-replica count never dips below quorum (and refuses to
  start when it would);
- retries honor the rider's ``remaining_ms``: a tight-deadline request
  sheds typed (``DeadlineExceeded``) instead of burning a retry whose
  backoff cannot fit — the deadline is never reset by retrying;
- every submitted request resolves to exactly one typed outcome —
  ``submitted == ok + sheds + failures + cancelled`` reconciles
  exactly, with one ``kind="fleet"`` span per request under one trace
  id;
- the fleet ``/healthz`` aggregate answers 200 while quorum holds
  (``"degraded"`` when any replica is) and 503 below quorum.

The router's race windows (choose vs admin flips vs retry timers vs
completion callbacks) are hammered across >= 100 amplified interleave
seeds in the slow tier (``-m interleave``), over stub-searcher engines
so a seed costs milliseconds, not device time.
"""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from raft_tpu import serving
from raft_tpu.neighbors import ivf_flat
from raft_tpu.obs.spans import ListSink
from raft_tpu.serving.engine import solo_reference
from raft_tpu.testing import faults

pytestmark = pytest.mark.fast

DIM = 16
K = 5


@pytest.fixture(scope="module")
def flat_index():
    rng = np.random.default_rng(3)
    db = rng.standard_normal((1500, DIM)).astype(np.float32)
    return ivf_flat.build(db, ivf_flat.IndexParams(n_lists=16))


def _searcher(flat_index):
    # fresh handle per replica: injectors rebind .search per handle, so
    # a fault armed on one replica never leaks to a sibling
    return serving.ivf_flat_searcher(flat_index,
                                     ivf_flat.SearchParams(n_probes=8))


def _fleet(flat_index, n=2, sink=None, engine_kw=None, **fleet_kw):
    ekw = {"max_batch": 8, "max_wait_us": 5000, "warm_ks": (K,)}
    ekw.update(engine_kw or {})
    fleet_kw.setdefault("quorum", 1)
    fleet_kw.setdefault("seed", 7)
    fleet_kw.setdefault("probe_interval_s", 0.05)
    cfg = serving.FleetConfig(span_sink=sink, **fleet_kw)
    return serving.Fleet.from_searchers(
        [_searcher(flat_index) for _ in range(n)],
        engine_config=serving.EngineConfig(**ekw), config=cfg)


def _q(rng):
    return rng.standard_normal(DIM).astype(np.float32)


def _reconcile(fleet):
    """Every submitted request resolved to exactly one typed outcome."""
    oc = fleet.stats.outcome_counts()
    resolved = sum(v for k, v in oc.items() if k != "submitted")
    assert oc["submitted"] == resolved, f"silent loss: {oc}"
    return oc


def _assert_bit_identical(fut, query):
    d, i = fut.result(timeout=0)
    ref_d, ref_i = solo_reference(fut.searcher, query, K, *fut.placement)
    assert np.array_equal(d, ref_d) and np.array_equal(i, ref_i)


# ------------------------------------------------- replica kill retries
def test_replica_kill_mid_batch_retries_on_sibling(flat_index):
    """Kill replica0 with riders queued mid-batch: every future still
    resolves ok — retried on the sibling — and every result is
    bit-identical to a solo search on the replica that served it."""
    sink = ListSink()
    fleet = _fleet(flat_index, n=2, sink=sink)
    rng = np.random.default_rng(0)
    with fleet:
        r0 = fleet.replicas[0]
        # slow r0 so a backlog builds there, guaranteeing the kill
        # catches queued/in-flight riders (not an idle engine)
        restore = faults._wrap_search(
            r0.engine.searcher,
            lambda orig, q, k: (time.sleep(0.05), orig(q, k))[1])
        queries = [_q(rng) for _ in range(60)]
        futs = [fleet.submit(q, K) for q in queries]
        deadline = time.monotonic() + 10
        while (len(r0.engine.batcher) == 0
               and time.monotonic() < deadline):
            time.sleep(0.001)
        assert len(r0.engine.batcher) > 0, "no backlog built on r0"
        faults.kill_replica(fleet, "replica0")
        restore()
        for q, f in zip(queries, futs):
            f.result(timeout=30)
            _assert_bit_identical(f, q)
        oc = _reconcile(fleet)
        assert oc["ok"] == len(queries)
        # the kill's casualties were retried on the sibling, typed
        retried = fleet.stats._retried
        total_retries = sum(int(c.value)
                            for (rep, _), c in retried.items()
                            if rep == "replica0")
        assert total_retries > 0, "kill produced no sibling retries"
    # one fleet span per request, each under its own single trace id
    spans = [r for r in sink.records if r["kind"] == "fleet"]
    assert len(spans) == len(queries)
    assert len({s["trace_id"] for s in spans}) == len(queries)
    for s in spans:
        assert s["outcome"] == "ok"
        assert all(("trace" in a) or ("error" in a)
                   for a in s["attempts"])


def test_injected_batch_failure_retries_bit_identically(flat_index):
    """A transient mid-batch device failure (BatchFailed) on one replica
    is retried on a sibling with a bit-identical result, not surfaced
    to the caller."""
    fleet = _fleet(flat_index, n=2)
    rng = np.random.default_rng(1)
    with fleet:
        disarm = faults.fail_next_dispatch(
            fleet.replicas[0].engine.searcher, times=5)
        queries = [_q(rng) for _ in range(30)]
        futs = [fleet.submit(q, K) for q in queries]
        for q, f in zip(queries, futs):
            f.result(timeout=30)
            _assert_bit_identical(f, q)
        disarm()
        oc = _reconcile(fleet)
        assert oc["ok"] == len(queries)


# --------------------------------------------- breaker route-around
class _FakeClock:
    """Injectable clock for breaker/probe timing (the host_p2p test
    pattern): timing *decisions* read this, so no amount of real CI
    load can make a cooldown elapse early or a probe window slip."""

    def __init__(self, t: float = 0.0):
        self._t = t
        self._lock = threading.Lock()

    def advance(self, dt: float) -> None:
        with self._lock:
            self._t += dt

    def __call__(self) -> float:
        with self._lock:
            return self._t


def test_breaker_open_routed_around_then_readmitted(flat_index):
    """A breaker-open replica takes no regular traffic, but the router's
    rate-limited probes re-admit it once the half-open probe batch
    closes the breaker.

    Deflaked (PR 16 note): the breaker cooldown and the router's probe
    interval are huge in REAL time (60 s / 10 s) and driven entirely by
    a fake clock — under parallel CI load nothing can flip early, and
    re-admission happens exactly when the test advances time."""
    fleet = _fleet(flat_index, n=2, probe_interval_s=10.0,
                   engine_kw={"breaker_cooldown_s": 60.0})
    rng = np.random.default_rng(2)
    with fleet:
        clk = _FakeClock()
        r0 = fleet.replicas[0].engine
        # move ONLY the timing decisions onto the fake clock: the
        # breaker's cooldown arithmetic and the router's probe
        # rate-limit. Batching/dispatch keep the real clock (their
        # waits must actually elapse).
        r0.breaker.clock = clk
        fleet.router.clock = clk
        faults.trip_breaker(fleet, "replica0")
        assert r0.health()["status"] == "unhealthy"
        assert fleet.health()["status"] == "degraded"
        # traffic keeps flowing around the sick replica, typed retries
        # absorbing any too-early probes (CircuitOpen -> sibling);
        # fake time stands still, so the breaker CANNOT close here
        for _ in range(10):
            fleet.search(_q(rng), K, timeout=30)
        assert r0.health()["status"] == "unhealthy", \
            "breaker closed with no cooldown elapsed"
        # advance past the cooldown: the next due probe goes half-open
        # and its completion closes the breaker
        clk.advance(61.0)
        for _ in range(30):
            fleet.search(_q(rng), K, timeout=30)
            if r0.health()["status"] == "ok":
                break
            clk.advance(10.5)  # next probe window
        assert r0.health()["status"] == "ok", "probe never closed breaker"
        assert fleet.health()["status"] == "ok"
        routed_before = int(fleet.stats._routed["replica0"].value)
        for _ in range(40):
            fleet.search(_q(rng), K, timeout=30)
        assert int(fleet.stats._routed["replica0"].value) > routed_before, \
            "re-admitted replica got no traffic"
        _reconcile(fleet)


# -------------------------------------------------- rolling swap + quorum
def test_rolling_swap_under_load_zero_drops_never_below_quorum(flat_index):
    """rolling_swap with concurrent submitters: zero dropped requests,
    every result bit-identical on its serving handle, and the healthy
    in-service count sampled throughout never dips below quorum."""
    fleet = _fleet(flat_index, n=3, quorum=2)
    rng = np.random.default_rng(4)
    results = []
    lock = threading.Lock()
    stop_sampling = threading.Event()
    quorum_samples = []

    def sampler():
        while not stop_sampling.is_set():
            quorum_samples.append(fleet.healthy_count())
            time.sleep(0.002)

    def submitter(ti):
        trng = np.random.default_rng(100 + ti)
        for _ in range(40):
            q = _q(trng)
            f = fleet.submit(q, K)
            with lock:
                results.append((q, f))

    with fleet:
        threads = [threading.Thread(target=submitter, args=(ti,))
                   for ti in range(3)]
        sam = threading.Thread(target=sampler)
        sam.start()
        for t in threads:
            t.start()
        old = fleet.rolling_swap([_searcher(flat_index)
                                  for _ in range(3)])
        for t in threads:
            t.join()
        assert fleet.drain(timeout=60)
        stop_sampling.set()
        sam.join()
        assert all(o is not None for o in old)
        assert quorum_samples and min(quorum_samples) >= 2, \
            f"quorum dipped: min={min(quorum_samples or [0])}"
        for q, f in results:
            assert f.done()
            _assert_bit_identical(f, q)
        oc = _reconcile(fleet)
        assert oc["ok"] == len(results)
        assert fleet.stats._swaps.value == 3


def test_rolling_swap_refuses_below_quorum(flat_index):
    """Draining any replica of a 2-replica quorum-2 fleet would leave 1
    healthy — the swap must refuse before touching anything."""
    fleet = _fleet(flat_index, n=2, quorum=2)
    with fleet:
        gens_before = [r.engine._searcher_gen for r in fleet.replicas]
        with pytest.raises(serving.FleetBelowQuorum):
            fleet.rolling_swap([_searcher(flat_index) for _ in range(2)])
        assert [r.engine._searcher_gen
                for r in fleet.replicas] == gens_before
        assert all(r.admin == "in_service" for r in fleet.replicas)


# -------------------------------------------------- deadline discipline
def test_tight_deadline_sheds_typed_instead_of_retrying(flat_index):
    """A request whose deadline expires while queued sheds typed
    (DeadlineExceeded) with NO retry: the rider's budget is spent and
    no sibling can un-spend it."""
    # huge flush deadline: a lone request sits queued well past its
    # 30 ms shed deadline, so the engine-side shed path fires
    fleet = _fleet(flat_index, n=2,
                   engine_kw={"max_wait_us": 2_000_000})
    rng = np.random.default_rng(5)
    with fleet:
        fut = fleet.submit(_q(rng), K, deadline_ms=30.0)
        with pytest.raises(serving.DeadlineExceeded):
            fut.result(timeout=10)
        oc = _reconcile(fleet)
        assert oc["shed_deadline"] == 1
        retried = sum(int(c.value)
                      for c in fleet.stats._retried.values())
        assert retried == 0, "deadline shed must not burn retries"


def test_retry_backoff_honors_remaining_ms(flat_index):
    """When the drawn backoff cannot fit the rider's remaining budget
    the request sheds DeadlineExceeded immediately (cause chained)
    instead of sleeping past its own deadline: a retry never resets or
    outlives the deadline."""
    # single replica: the first BatchFailed wants a retry; with
    # seed=0 the full-jitter draw over [0, 200] ms is ~169 ms >> the
    # ~1 s budget remaining is... see below: deadline 2 s minus the
    # instant failure leaves < 200 ms only with a tight deadline
    fleet = _fleet(flat_index, n=1, seed=0, retry_limit=4,
                   backoff_base_ms=4000.0, backoff_cap_ms=4000.0)
    rng = np.random.default_rng(6)
    with fleet:
        disarm = faults.fail_next_dispatch(
            fleet.replicas[0].engine.searcher, times=10)
        t0 = time.perf_counter()
        fut = fleet.submit(_q(rng), K, deadline_ms=2000.0)
        with pytest.raises(serving.DeadlineExceeded) as ei:
            fut.result(timeout=30)
        elapsed = time.perf_counter() - t0
        disarm()
        # shed the moment the draw (uniform[0, 4000) ms, seeded, far
        # above the remaining budget at every plausible draw) could not
        # fit — NOT after sleeping the backoff or the full deadline
        assert elapsed < 1.5, f"slept into the backoff: {elapsed:.2f}s"
        assert isinstance(ei.value.__cause__, serving.BatchFailed)
        oc = _reconcile(fleet)
        assert oc["shed_deadline"] == 1


# ------------------------------------------------- typed shed exhaustion
def test_all_replicas_dead_sheds_typed(flat_index):
    """With every replica killed, a submit resolves typed
    (NoReplicaAvailable, an Overloaded) — never raises raw, never
    hangs, never lost."""
    fleet = _fleet(flat_index, n=2)
    rng = np.random.default_rng(7)
    with fleet:
        faults.kill_replica(fleet, 0)
        faults.kill_replica(fleet, 1)
        fut = fleet.submit(_q(rng), K)
        with pytest.raises(serving.NoReplicaAvailable):
            fut.result(timeout=10)
        assert isinstance(fut.exception(), serving.Overloaded)
        oc = _reconcile(fleet)
        assert oc["shed_no_replica"] == 1


def test_fleet_stop_strands_no_future(flat_index):
    """stop(drain=False) racing live submissions: every outstanding
    future resolves typed (EngineStopped / outcome accounting exact)."""
    fleet = _fleet(flat_index, n=2)
    rng = np.random.default_rng(8)
    with fleet:
        futs = [fleet.submit(_q(rng), K) for _ in range(40)]
        fleet.stop(drain=False)
        for f in futs:
            assert f.done(), "stranded future after stop"
            if f.exception() is not None:
                assert isinstance(f.exception(),
                                  serving.EngineStopped)
        _reconcile(fleet)


# ------------------------------------------------------ healthz aggregate
def test_healthz_aggregates_fleet_state(flat_index):
    """One scrape target for the fleet: 200 "ok" with all replicas up,
    200 "degraded" with a replica dead but quorum held, 503 below
    quorum."""
    fleet = _fleet(flat_index, n=3, quorum=2)
    with fleet:
        srv = fleet.serve_metrics(port=0)
        url = f"http://127.0.0.1:{srv.port}/healthz"

        def get():
            try:
                with urllib.request.urlopen(url, timeout=10) as r:
                    return r.status, json.loads(r.read())
            except urllib.error.HTTPError as e:
                return e.code, json.loads(e.read())

        code, doc = get()
        assert code == 200 and doc["status"] == "ok"
        assert doc["quorum"] == {"required": 2, "healthy": 3, "ok": True}
        faults.kill_replica(fleet, "replica2")
        code, doc = get()
        assert code == 200 and doc["status"] == "degraded"
        assert doc["quorum"]["healthy"] == 2
        assert doc["replicas"]["replica2"]["status"] == "unhealthy"
        faults.kill_replica(fleet, "replica1")
        code, doc = get()
        assert code == 503 and doc["status"] == "unhealthy"
        assert doc["quorum"]["ok"] is False


# ---------------------------------------------- typed hierarchy (exports)
def test_typed_failure_hierarchy_and_retryability():
    """Satellite pin: the full hierarchy is exported from raft_tpu.serving
    and the router classifies by isinstance exactly as the package
    docstring's table says."""
    for name in ("BatchFailed", "Overloaded", "CircuitOpen",
                 "DeadlineExceeded", "IntegrityError", "QueueFull",
                 "EngineStopped", "NoReplicaAvailable",
                 "RetriesExhausted", "FleetBelowQuorum",
                 "ReplicaStarting"):
        assert name in serving.__all__, name
        assert hasattr(serving, name), name
    assert issubclass(serving.CircuitOpen, serving.Overloaded)
    assert issubclass(serving.NoReplicaAvailable, serving.Overloaded)
    assert issubclass(serving.RetriesExhausted, serving.Overloaded)
    assert issubclass(serving.ReplicaStarting, serving.Overloaded)
    assert serving.is_retryable(serving.ReplicaStarting("x"))
    assert serving.failure_kind(
        serving.ReplicaStarting("x")) == "replica_starting"
    assert serving.is_retryable(serving.BatchFailed("x"))
    assert serving.is_retryable(serving.Overloaded("x"))
    assert serving.is_retryable(serving.CircuitOpen("x"))
    assert serving.is_retryable(serving.QueueFull("x"))
    assert serving.is_retryable(serving.EngineStopped("x"))
    assert not serving.is_retryable(serving.DeadlineExceeded("x"))
    assert not serving.is_retryable(serving.IntegrityError("x"))
    assert not serving.is_retryable(ValueError("x"))
    # labels come from type, not message text
    assert serving.failure_kind(
        serving.CircuitOpen("overloaded-looking text")) == "circuit_open"


# ------------------------------------- amplified interleavings (slow tier)
class _StubIndex:
    pass


def _stub_searcher(dim=8):
    """Pure-numpy handle: deterministic per-query rows (so sibling
    replicas are bit-identical by construction) at microsecond cost —
    makes 100-seed amplified fleets affordable."""

    def search(queries, k):
        q = np.asarray(queries, np.float32)
        base = q.sum(axis=1, keepdims=True)
        d = base + np.arange(k, dtype=np.float32)[None, :]
        i = (np.abs(q).sum(axis=1, keepdims=True).astype(np.int64)
             + np.arange(k, dtype=np.int64)[None, :])
        return d.astype(np.float32), i

    return serving.Searcher(family="stub", dim=dim, index=_StubIndex(),
                            search=search)


@pytest.mark.slow
@pytest.mark.interleave
def test_router_races_amplified(flat_index):
    """Hammer the router/fleet race windows — choose vs admin flips vs
    retry timers vs completion callbacks vs stop — across >= 100
    amplified interleave seeds: at every seed, every future resolves
    typed and the outcome accounting reconciles exactly (zero silent
    losses). Seed base via RAFT_TPU_INTERLEAVE_SEED."""
    from raft_tpu.testing.interleave import InterleaveAmplifier, seeds

    DIM_S = 8
    for seed in seeds(100):
        cfg = serving.FleetConfig(quorum=1, seed=seed, retry_limit=4,
                                  backoff_base_ms=0.2,
                                  backoff_cap_ms=2.0,
                                  probe_interval_s=0.01)
        ecfg = serving.EngineConfig(
            max_batch=4, max_wait_us=200, warm_ks=(K,),
            hang_timeout_s=None, persistent_cache=False,
            flight_recorder=False)
        fleet = serving.Fleet.from_searchers(
            [_stub_searcher(DIM_S) for _ in range(3)],
            engine_config=ecfg, config=cfg)
        futs = []
        lock = threading.Lock()

        def submitter(ti, fleet=fleet, futs=futs, lock=lock):
            trng = np.random.default_rng(1000 + ti)
            for _ in range(15):
                q = trng.standard_normal(DIM_S).astype(np.float32)
                try:
                    f = fleet.submit(q, K)
                except serving.EngineStopped:
                    return
                with lock:
                    futs.append(f)

        def chaos(fleet=fleet):
            faults.fail_next_dispatch(
                fleet.replicas[0].engine.searcher, times=3)
            try:
                fleet.rolling_swap([_stub_searcher(DIM_S)
                                    for _ in range(3)],
                                   warm=False)
            except serving.FleetBelowQuorum:
                pass
            faults.kill_replica(fleet, "replica2")

        with InterleaveAmplifier(seed=seed, yield_probability=0.05,
                                 path_filters=("raft_tpu/serving",)):
            fleet.start()
            threads = [threading.Thread(target=submitter, args=(ti,))
                       for ti in range(3)]
            threads.append(threading.Thread(target=chaos))
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert fleet.drain(timeout=60), f"seed {seed}: drain hung"
            fleet.stop(drain=False)

        for f in futs:
            assert f.done(), f"seed {seed}: stranded future"
            exc = f.exception()
            if exc is not None:
                assert isinstance(
                    exc, (serving.Overloaded, serving.BatchFailed,
                          serving.EngineStopped,
                          serving.DeadlineExceeded)), (seed, exc)
        oc = fleet.stats.outcome_counts()
        resolved = sum(v for k, v in oc.items() if k != "submitted")
        assert oc["submitted"] == resolved, (seed, oc)
        assert oc["submitted"] == len(futs), (seed, oc)
