"""Aux core subsystems: tracing ranges, interruptible sync, pallas kernel
(interpret mode) — reference: core/nvtx.hpp, core/interruptible.hpp,
distance/fused_l2_nn-inl.cuh."""

import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_tpu.core import interruptible, tracing


def test_tracing_range_context_and_decorator():
    with tracing.range("test::scope"):
        x = jnp.ones((4,)) * 2

    @tracing.annotate("test::fn")
    def fn(a):
        return a + 1

    np.testing.assert_array_equal(np.asarray(fn(x)), 3.0)


def test_tracing_inside_jit():
    @jax.jit
    def f(a):
        with tracing.range("inner"):
            return a * 2

    assert float(f(jnp.float32(3.0))) == 6.0


def test_interruptible_synchronize_ready():
    x = jnp.ones((8, 8)) @ jnp.ones((8, 8))
    interruptible.synchronize(x)  # completes without raising


def test_interruptible_cancel():
    main_id = threading.get_ident()
    interruptible.cancel(main_id)
    with pytest.raises(interruptible.InterruptedException):
        interruptible.yield_now()
    # token cleared after raise: next sync passes
    interruptible.synchronize(jnp.ones((2,)))


def test_interruptible_cancel_from_other_thread():
    target_ready = threading.Event()
    result = {}

    def worker():
        result["tid"] = threading.get_ident()
        target_ready.set()
        try:
            while True:
                interruptible.yield_now()
                time.sleep(0.005)
        except interruptible.InterruptedException:
            result["cancelled"] = True

    t = threading.Thread(target=worker)
    t.start()
    target_ready.wait()
    interruptible.cancel(result["tid"])
    t.join(timeout=5)
    assert result.get("cancelled")


def test_pallas_fused_l2_argmin_interpret(rng):
    from raft_tpu.ops import pallas_kernels as pk

    x = rng.standard_normal((100, 32)).astype(np.float32)
    y = rng.standard_normal((300, 32)).astype(np.float32)
    v, i = pk.fused_l2_argmin(x, y, interpret=True)
    d = ((x[:, None, :] - y[None, :, :]) ** 2).sum(-1)
    np.testing.assert_array_equal(np.asarray(i), d.argmin(1))
    np.testing.assert_allclose(np.asarray(v), d.min(1), rtol=1e-3, atol=1e-3)


def test_pallas_fused_l2_argmin_unaligned(rng):
    from raft_tpu.ops import pallas_kernels as pk

    # shapes that aren't multiples of the tile sizes
    x = rng.standard_normal((37, 24)).astype(np.float32)
    y = rng.standard_normal((131, 24)).astype(np.float32)
    v, i = pk.fused_l2_argmin(x, y, tm=16, tn=128, interpret=True)
    d = ((x[:, None, :] - y[None, :, :]) ** 2).sum(-1)
    np.testing.assert_array_equal(np.asarray(i), d.argmin(1))


# ---------------------------------------------------------------------------
# operators / errors / resources_manager (core/operators.hpp, core/error.hpp,
# core/device_resources_manager.hpp)

def test_operators():
    from raft_tpu.core import operators as ops

    x = jnp.asarray([1.0, -2.0, 3.0])
    np.testing.assert_allclose(np.asarray(ops.sq_op(x)), [1, 4, 9])
    np.testing.assert_allclose(np.asarray(ops.abs_op(x)), [1, 2, 3])
    np.testing.assert_allclose(
        np.asarray(ops.compose_op(ops.sqrt_op, ops.abs_op)(x)),
        np.sqrt([1, 2, 3]), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(ops.div_checkzero_op(x, jnp.asarray([1.0, 0.0, 2.0]))),
        [1.0, 0.0, 1.5])
    addc = ops.add_const_op(10.0)
    np.testing.assert_allclose(np.asarray(addc(x)), [11, 8, 13])
    mapped = ops.map_args_op(ops.add_op, ops.sq_op, ops.abs_op)
    np.testing.assert_allclose(np.asarray(mapped(x, x)), [2, 6, 12])


def test_errors():
    import pytest
    from raft_tpu.core import errors

    errors.expects(True, "fine")
    with pytest.raises(errors.LogicError):
        errors.expects(False, "boom")
    with pytest.raises(errors.LogicError):
        errors.fail("nope")
    assert issubclass(errors.LogicError, errors.RaftError)


def test_resources_manager_round_robin():
    from raft_tpu.core import resources_manager as rm

    rm.reset()
    rm.set_resources_per_device(3)
    got = [rm.get_resources() for _ in range(4)]
    assert got[0] is got[3]          # pool of 3 wraps around
    assert len({id(r) for r in got[:3]}) == 3
    # options are frozen after first hand-out (reference semantics)
    rm.set_resources_per_device(5)
    got2 = [rm.get_resources() for _ in range(5)]
    assert len({id(r) for r in got2}) == 3
    rm.reset()


# ---------------------------------------------------------------------------
# label / solver / spatial namespaces

def test_make_monotonic_and_unique():
    from raft_tpu import label

    labs = np.array([7, 3, 7, 9, 3, -1], np.int32)
    mono = np.asarray(label.make_monotonic(labs, max_labels=8))
    assert mono[0] == mono[2] and mono[1] == mono[4]
    assert set(mono[[0, 1, 3]]) == {0, 1, 2}
    assert mono[5] == -1
    uniq, n = label.get_unique_labels(labs[:-1], max_labels=8)
    assert int(n) == 3
    assert list(np.asarray(uniq)[:3]) == [3, 7, 9]


def test_merge_labels():
    from raft_tpu import label

    # a: {0,1},{2,3}; b: {1,2},{0},{3} → all four merge into one group
    a = np.array([0, 0, 1, 1], np.int32)
    b = np.array([0, 1, 1, 2], np.int32)
    out = np.asarray(label.merge_labels(a, b))
    assert len(set(out)) == 1
    # disjoint groups stay separate
    a = np.array([0, 0, 1, 1], np.int32)
    b = np.array([2, 2, 3, 3], np.int32)
    out = np.asarray(label.merge_labels(a, b))
    assert out[0] == out[1] and out[2] == out[3] and out[0] != out[2]


def test_lap_auction_matches_scipy(rng):
    from raft_tpu import solver

    for n in (5, 12):
        cost = rng.random((n, n)).astype(np.float32)
        assign, total = solver.solve(cost)
        ref_assign, ref_total = solver.solve_host(cost)
        assign = np.asarray(assign)
        assert (assign >= 0).all() and len(set(assign.tolist())) == n
        # auction is eps-optimal: within n*eps of the exact optimum
        assert float(total) <= ref_total + n * (1.0 / (n + 1)) + 1e-4


def test_spatial_namespace(rng):
    from raft_tpu import spatial

    db = rng.standard_normal((50, 8)).astype(np.float32)
    q = rng.standard_normal((5, 8)).astype(np.float32)
    d, i = spatial.knn.knn(db, q, k=3, metric="sqeuclidean")
    ref = ((q[:, None, :] - db[None, :, :]) ** 2).sum(-1)
    np.testing.assert_array_equal(np.asarray(i)[:, 0], ref.argmin(1))
    pts = np.radians([[51.5, -0.13], [48.86, 2.35]]).astype(np.float32)
    h = np.asarray(spatial.haversine_distance(pts, pts))
    assert h.shape == (2, 2) and h[0, 1] > 0


def test_pallas_ivf_scan_interpret(rng):
    from raft_tpu.ops import pallas_kernels as pk

    L, pad, rot, nq, P = 6, 16, 8, 5, 3
    dec = rng.standard_normal((L, pad, rot)).astype(np.float32)
    norms = (dec ** 2).sum(-1).astype(np.float32)
    probes = rng.integers(0, L, (nq, P)).astype(np.int32)
    qres = rng.standard_normal((nq, P, rot)).astype(np.float32)
    out = np.asarray(pk.ivf_scan(probes, qres, dec, norms, interpret=True))
    ref = np.stack([
        np.stack([norms[probes[i, j]]
                  - 2.0 * dec[probes[i, j]] @ qres[i, j]
                  for j in range(P)]) for i in range(nq)])
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


def test_device_ndarray_torch_interop():
    """pylibraft's cai_wrapper role: foreign-framework tensors (torch CPU)
    convert through device_ndarray/to_host without copying semantics
    surprises."""
    torch = pytest.importorskip("torch")
    from raft_tpu import common

    t = torch.arange(12, dtype=torch.float32).reshape(3, 4)
    a = common.device_ndarray(t)
    assert a.shape == (3, 4)
    np.testing.assert_array_equal(common.to_host(a), t.numpy())


def test_balanced_tile():
    """Tile-grid balancing: even splits, bounded padding, budget never
    exceeded, empty input degrades to 1 (shape.balanced_tile)."""
    from raft_tpu.utils.shape import balanced_tile, cdiv

    assert balanced_tile(10_000, 10_000, 128) == 10_000  # single tile
    assert balanced_tile(0, 4096, 128) == 1
    assert balanced_tile(5, 3, 8) == 3  # alignment yields to budget
    # budget tile below the multiple never inflates (workspace invariant)
    assert balanced_tile(1_000_000, 33, 128) <= 33
    assert balanced_tile(1_000_000, 1, 8) == 1
    for total, tile, mult in [(200_000, 131_072, 128), (10_000, 4_096, 8),
                              (131_073, 65_536, 128), (999, 1024, 128),
                              (1_000_000, 131_072, 128)]:
        t = balanced_tile(total, tile, mult)
        assert 1 <= t <= max(tile, 1)
        n_tiles = cdiv(total, t)
        assert n_tiles * t - total < mult * n_tiles + mult, (total, tile, t)
