"""Aux core subsystems: tracing ranges, interruptible sync, pallas kernel
(interpret mode) — reference: core/nvtx.hpp, core/interruptible.hpp,
distance/fused_l2_nn-inl.cuh."""

import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_tpu.core import interruptible, tracing


def test_tracing_range_context_and_decorator():
    with tracing.range("test::scope"):
        x = jnp.ones((4,)) * 2

    @tracing.annotate("test::fn")
    def fn(a):
        return a + 1

    np.testing.assert_array_equal(np.asarray(fn(x)), 3.0)


def test_tracing_inside_jit():
    @jax.jit
    def f(a):
        with tracing.range("inner"):
            return a * 2

    assert float(f(jnp.float32(3.0))) == 6.0


def test_interruptible_synchronize_ready():
    x = jnp.ones((8, 8)) @ jnp.ones((8, 8))
    interruptible.synchronize(x)  # completes without raising


def test_interruptible_cancel():
    main_id = threading.get_ident()
    interruptible.cancel(main_id)
    with pytest.raises(interruptible.InterruptedException):
        interruptible.yield_now()
    # token cleared after raise: next sync passes
    interruptible.synchronize(jnp.ones((2,)))


def test_interruptible_cancel_from_other_thread():
    target_ready = threading.Event()
    result = {}

    def worker():
        result["tid"] = threading.get_ident()
        target_ready.set()
        try:
            while True:
                interruptible.yield_now()
                time.sleep(0.005)
        except interruptible.InterruptedException:
            result["cancelled"] = True

    t = threading.Thread(target=worker)
    t.start()
    target_ready.wait()
    interruptible.cancel(result["tid"])
    t.join(timeout=5)
    assert result.get("cancelled")


def test_pallas_fused_l2_argmin_interpret(rng):
    from raft_tpu.ops import pallas_kernels as pk

    x = rng.standard_normal((100, 32)).astype(np.float32)
    y = rng.standard_normal((300, 32)).astype(np.float32)
    v, i = pk.fused_l2_argmin(x, y, interpret=True)
    d = ((x[:, None, :] - y[None, :, :]) ** 2).sum(-1)
    np.testing.assert_array_equal(np.asarray(i), d.argmin(1))
    np.testing.assert_allclose(np.asarray(v), d.min(1), rtol=1e-3, atol=1e-3)


def test_pallas_fused_l2_argmin_unaligned(rng):
    from raft_tpu.ops import pallas_kernels as pk

    # shapes that aren't multiples of the tile sizes
    x = rng.standard_normal((37, 24)).astype(np.float32)
    y = rng.standard_normal((131, 24)).astype(np.float32)
    v, i = pk.fused_l2_argmin(x, y, tm=16, tn=128, interpret=True)
    d = ((x[:, None, :] - y[None, :, :]) ** 2).sum(-1)
    np.testing.assert_array_equal(np.asarray(i), d.argmin(1))


# ---------------------------------------------------------------------------
# operators / errors / resources_manager (core/operators.hpp, core/error.hpp,
# core/device_resources_manager.hpp)

def test_operators():
    from raft_tpu.core import operators as ops

    x = jnp.asarray([1.0, -2.0, 3.0])
    np.testing.assert_allclose(np.asarray(ops.sq_op(x)), [1, 4, 9])
    np.testing.assert_allclose(np.asarray(ops.abs_op(x)), [1, 2, 3])
    np.testing.assert_allclose(
        np.asarray(ops.compose_op(ops.sqrt_op, ops.abs_op)(x)),
        np.sqrt([1, 2, 3]), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(ops.div_checkzero_op(x, jnp.asarray([1.0, 0.0, 2.0]))),
        [1.0, 0.0, 1.5])
    addc = ops.add_const_op(10.0)
    np.testing.assert_allclose(np.asarray(addc(x)), [11, 8, 13])
    mapped = ops.map_args_op(ops.add_op, ops.sq_op, ops.abs_op)
    np.testing.assert_allclose(np.asarray(mapped(x, x)), [2, 6, 12])


def test_errors():
    import pytest
    from raft_tpu.core import errors

    errors.expects(True, "fine")
    with pytest.raises(errors.LogicError):
        errors.expects(False, "boom")
    with pytest.raises(errors.LogicError):
        errors.fail("nope")
    assert issubclass(errors.LogicError, errors.RaftError)


def test_resources_manager_round_robin():
    from raft_tpu.core import resources_manager as rm

    rm.reset()
    rm.set_resources_per_device(3)
    got = [rm.get_resources() for _ in range(4)]
    assert got[0] is got[3]          # pool of 3 wraps around
    assert len({id(r) for r in got[:3]}) == 3
    # options are frozen after first hand-out (reference semantics)
    rm.set_resources_per_device(5)
    got2 = [rm.get_resources() for _ in range(5)]
    assert len({id(r) for r in got2}) == 3
    rm.reset()
