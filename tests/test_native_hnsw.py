"""Native C++ runtime + HNSW export tests (reference: bench dataset.hpp bin
IO, detail/hnsw_types.hpp serializer, detail/agglomerative.cuh labeling,
detail/ivf_flat_build.cuh list fill)."""

import os

import numpy as np
import pytest

from raft_tpu import native


def test_native_builds():
    assert native.ensure_built(), "g++ build of libraft_tpu_native.so failed"
    assert native.available()


def _golden_bytes(data, graph, max_level, enterpoint, mult, ef):
    """Hand-packed hnswlib saveIndex bytes, authored independently from the
    reference serializer's field list (cagra_serialize.cuh:113-202)."""
    import struct

    n, dim = data.shape
    degree = graph.shape[1]
    size_links0 = degree * 4 + 4
    size_per_elem = size_links0 + dim * 4 + 8
    return b"".join([
        struct.pack("<Q", 0),                  # offset_level_0
        struct.pack("<Q", n),                  # max_element
        struct.pack("<Q", n),                  # curr_element_count
        struct.pack("<Q", size_per_elem),      # size_data_per_element
        struct.pack("<Q", size_per_elem - 8),  # label_offset
        struct.pack("<Q", size_links0),        # offset_data
        struct.pack("<i", max_level),
        struct.pack("<i", enterpoint),
        struct.pack("<Q", degree // 2),        # max_M
        struct.pack("<Q", degree),             # max_M0
        struct.pack("<Q", degree // 2),        # M
        struct.pack("<d", mult),
        struct.pack("<Q", ef),                 # efConstruction
        # per element: [int link_count][degree x uint32][dim x f32][size_t]
        *(struct.pack("<i", degree)
          + graph[i].astype("<u4").tobytes()
          + data[i].astype("<f4").tobytes()
          + struct.pack("<Q", i)
          for i in range(n)),
        *[struct.pack("<i", 0)] * n,           # linkListSize zeros
    ])


def test_hnswlib_golden_byte_layout(tmp_path):
    """Byte-for-byte gate of the native hnswlib writer against hand-packed
    fixtures — not a round-trip through our own parser (VERDICT r1 #8).
    ``compat="raft"`` must equal the reference serializer's output
    (cagra_serialize.cuh:113-202, the base_layer_only loader contract of
    hnsw_types.hpp:60-86); ``compat="hnswlib"`` must emit the stock-safe
    max_level=0/enterpoint=0 header."""
    n, dim, degree = 3, 2, 2
    data = np.arange(n * dim, dtype=np.float32).reshape(n, dim) * 0.5
    graph = np.array([[1, 2], [0, 2], [0, 1]], np.int32)

    for compat, (lvl, ep, mult, ef) in {
        "raft": (1, n // 2, 0.42424242, 500),
        "hnswlib": (0, 0, 1.0 / np.log(max(degree // 2, 2)), 200),
    }.items():
        path = str(tmp_path / f"golden_{compat}.hnsw")
        native.hnswlib_write(path, data, graph, space="l2", compat=compat)
        got = open(path, "rb").read()
        want = _golden_bytes(data, graph, lvl, ep, mult, ef)
        assert got == want, (
            f"{compat}: diverges at byte "
            f"{next((i for i, (a, b) in enumerate(zip(got, want)) if a != b), 'len')}"
            f" (len {len(got)} vs {len(want)})")


def test_bin_roundtrip(tmp_path, rng):
    x = rng.standard_normal((100, 16)).astype(np.float32)
    p = str(tmp_path / "data.fbin")
    native.write_bin(p, x)
    n, d = native.read_bin_header(p)
    assert (n, d) == (100, 16)
    np.testing.assert_array_equal(native.read_bin(p), x)
    np.testing.assert_array_equal(native.read_bin(p, 10, 20), x[10:30])
    # batch iterator covers everything
    got = np.concatenate(
        [b for _, b in native.iter_bin_batches(p, 32)])
    np.testing.assert_array_equal(got, x)


def test_bin_ibin(tmp_path, rng):
    g = rng.integers(0, 1000, (50, 10)).astype(np.int32)
    p = str(tmp_path / "gt.ibin")
    native.write_bin(p, g)
    np.testing.assert_array_equal(native.read_bin(p), g)


def test_pack_lists_matches_numpy(rng):
    rows = rng.standard_normal((60, 8)).astype(np.float32)
    labels = rng.integers(0, 5, 60).astype(np.int32)
    data, ids, sizes = native.pack_lists(rows, labels, 5, 32)
    assert sizes.sum() == 60
    for l in range(5):
        members = np.nonzero(labels == l)[0]
        assert sizes[l] == len(members)
        assert set(ids[l, : sizes[l]].tolist()) == set(members.tolist())
        assert (ids[l, sizes[l]:] == -1).all()
        # rows land with their ids
        for p_ in range(sizes[l]):
            np.testing.assert_array_equal(data[l, p_], rows[ids[l, p_]])


def test_pack_lists_rejects_overflow(rng):
    rows = rng.standard_normal((20, 4)).astype(np.float32)
    labels = np.zeros(20, np.int32)
    with pytest.raises(ValueError):
        native.pack_lists(rows, labels, 2, 8)


def test_agglomerative_label_chain():
    # chain 0-1-2 and 3-4, cut into 2 clusters
    src = np.array([0, 1, 3], np.int32)
    dst = np.array([1, 2, 4], np.int32)
    labels = native.agglomerative_label(src, dst, 5, 2)
    assert labels[0] == labels[1] == labels[2]
    assert labels[3] == labels[4]
    assert labels[0] != labels[3]


@pytest.mark.slow
def test_hnswlib_export_roundtrip(tmp_path, rng):
    from raft_tpu.neighbors import brute_force, cagra, hnsw
    from raft_tpu.stats import neighborhood_recall

    db = rng.standard_normal((1000, 16)).astype(np.float32)
    q = rng.standard_normal((32, 16)).astype(np.float32)
    index = cagra.build(db, cagra.IndexParams(
        intermediate_graph_degree=32, graph_degree=16, nn_descent_niter=8))
    p = str(tmp_path / "index.hnsw")
    hnsw.from_cagra(index, p)
    assert os.path.getsize(p) > 1000 * 16 * 4  # at least the vectors

    loaded = hnsw.load(p)
    np.testing.assert_allclose(loaded.dataset, db, rtol=1e-6)
    # links round-trip (order preserved for valid entries)
    g = np.asarray(index.graph)
    np.testing.assert_array_equal(loaded.graph[:, : g.shape[1]], g)

    d, i = hnsw.search(loaded, q, k=5, ef=64)
    _, gt = brute_force.knn(q, db, k=5, metric="sqeuclidean")
    assert float(neighborhood_recall(i, np.asarray(gt))) >= 0.8


def test_hnswlib_python_fallback_writer(tmp_path, rng):
    from raft_tpu.neighbors import hnsw

    db = rng.standard_normal((50, 8)).astype(np.float32)
    graph = rng.integers(0, 50, (50, 8)).astype(np.int32)
    for compat in ("hnswlib", "raft"):
        p1 = str(tmp_path / f"c_{compat}.hnsw")
        p2 = str(tmp_path / f"py_{compat}.hnsw")
        native.hnswlib_write(p1, db, graph, compat=compat)
        native._hnswlib_write_py(p2, db, graph, compat)
        with open(p1, "rb") as f1, open(p2, "rb") as f2:
            assert f1.read() == f2.read(), \
                f"C++ and python writers must agree ({compat})"


def test_prefetch_iterator_matches_sync(tmp_path):
    """Native double-buffered reader yields identical batches to the
    synchronous iterator, including the ragged tail."""
    from raft_tpu import native

    rng = np.random.default_rng(3)
    data = rng.standard_normal((1037, 12)).astype(np.float32)
    path = str(tmp_path / "pf.fbin")
    native.write_bin(path, data)
    sync = list(native.iter_bin_batches(path, 128))
    pre = list(native.iter_bin_batches_prefetch(path, 128))
    assert len(sync) == len(pre)
    for (s0, b0), (s1, b1) in zip(sync, pre):
        assert s0 == s1
        np.testing.assert_array_equal(b0, b1)


def test_graph_greedy_search_exact_on_full_graph(rng):
    """ef-search on a COMPLETE graph must be exhaustive: every node is one
    hop from the entry, so top-k equals brute force exactly."""
    from raft_tpu import native

    n, dim = 200, 16
    db = rng.standard_normal((n, dim)).astype(np.float32)
    q = rng.standard_normal((5, dim)).astype(np.float32)
    full = np.broadcast_to(np.arange(n, dtype=np.int32), (n, n)).copy()
    d, i = native.graph_greedy_search(db, full, q, 10, ef=n)
    exact = ((q[:, None, :] - db[None]) ** 2).sum(-1)
    np.testing.assert_array_equal(i, np.argsort(exact, 1)[:, :10])
    np.testing.assert_allclose(d, np.sort(exact, 1)[:, :10], rtol=1e-5)


def test_graph_greedy_search_cpp_matches_python(rng):
    from raft_tpu import native

    n, dim, deg = 500, 8, 12
    db = rng.standard_normal((n, dim)).astype(np.float32)
    q = rng.standard_normal((20, dim)).astype(np.float32)
    graph = rng.integers(0, n, (n, deg)).astype(np.int32)
    graph[::7, -1] = -1  # ragged rows
    d1, i1 = native.graph_greedy_search(db, graph, q, 5, ef=32)
    d2, i2 = native._graph_greedy_search_py(db, graph, q, 5, 32, 0)
    np.testing.assert_array_equal(i1, i2)
    np.testing.assert_allclose(d1, d2, rtol=1e-5)


def test_graph_greedy_search_disconnected_pads(rng):
    """Unreachable components yield -1/inf pads, not garbage."""
    from raft_tpu import native

    db = rng.standard_normal((10, 4)).astype(np.float32)
    graph = np.full((10, 2), -1, np.int32)
    graph[0] = [1, 2]  # entry's component = {0, 1, 2}
    d, i = native.graph_greedy_search(db, graph, db[:1], 5, ef=8)
    assert set(i[0][:3]) == {0, 1, 2}
    assert (i[0][3:] == -1).all() and np.isinf(d[0][3:]).all()


def test_hnsw_cpu_engine_roundtrip(tmp_path, rng):
    """from_cagra -> load -> search(engine='cpu') runs hnswlib's own
    layer-0 algorithm over the exported file and must agree with the
    xla engine's recall on the same graph."""
    from raft_tpu.neighbors import cagra, hnsw

    db = rng.standard_normal((3000, 24)).astype(np.float32)
    q = rng.standard_normal((30, 24)).astype(np.float32)
    cg = cagra.build(db, cagra.IndexParams(graph_degree=16))
    path = str(tmp_path / "ix.hnsw")
    hnsw.from_cagra(cg, path)
    ix = hnsw.load(path)
    d_c, i_c = hnsw.search(ix, q, 5, ef=128, engine="cpu")
    d_x, i_x = hnsw.search(ix, q, 5, ef=128, engine="xla")
    exact = np.argsort(((q[:, None, :] - db[None]) ** 2).sum(-1), 1)[:, :5]
    rec_c = np.mean([len(set(r) & set(g)) / 5 for r, g in zip(i_c, exact)])
    rec_x = np.mean([len(set(r) & set(g)) / 5 for r, g in zip(i_x, exact)])
    assert rec_c >= 0.85, rec_c
    assert abs(rec_c - rec_x) < 0.2
    with pytest.raises(ValueError, match="l2"):
        hnsw.search(ix, q, 5, engine="cpu", space="ip")
