"""IVF-PQ tests — recall against exact ground truth with PQ-compression-aware
floors, the reference's acceptance pattern (cpp/test/neighbors/ann_ivf_pq.cuh:
build→(serialize→load)→search, recall floor from search params + compression)."""

import io

import numpy as np
import pytest

import jax.numpy as jnp

from raft_tpu.core.bitset import Bitset
from raft_tpu.neighbors import brute_force, ivf_pq
from raft_tpu.stats import neighborhood_recall


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(1)
    # clustered data — PQ on pure iid gaussian is adversarially hard
    centers = rng.standard_normal((50, 32)) * 4.0
    labels = rng.integers(0, 50, 4000)
    db = (centers[labels] + rng.standard_normal((4000, 32))).astype(np.float32)
    q = (centers[rng.integers(0, 50, 100)]
         + rng.standard_normal((100, 32))).astype(np.float32)
    return db, q


@pytest.fixture(scope="module")
def gt(data):
    db, q = data
    _, idx = brute_force.knn(q, db, k=10, metric="sqeuclidean")
    return np.asarray(idx)


def test_build_shapes(data):
    db, _ = data
    params = ivf_pq.IndexParams(n_lists=32, pq_dim=16, pq_bits=8)
    index = ivf_pq.build(db, params)
    assert index.n_lists == 32
    assert index.pq_dim == 16
    assert index.pq_len == 2  # rot_dim 32 / pq_dim 16
    assert index.size == len(db)
    assert index.codebooks.shape == (16, 256, 2)
    assert index.list_codes.shape[2] == 16 * 8 // 8
    # every row lives either in a list slot or in the overflow block
    n_over = int((np.asarray(index.overflow_indices) >= 0).sum())
    assert int(np.asarray(index.list_sizes).sum()) + n_over == len(db)
    # the padded-storage budget holds (VERDICT r2 #2)
    slots = (index.list_codes.shape[0] * index.list_codes.shape[1]
             + index.overflow_codes.shape[0])
    assert slots <= 1.5 * len(db) + 8 * index.n_lists


def test_rotation_orthonormal():
    import jax

    r = ivf_pq.make_rotation_matrix(jax.random.key(0), 48, 32, True)
    with jax.default_matmul_precision("highest"):
        rtr = np.asarray(r.T @ r)
    np.testing.assert_allclose(rtr, np.eye(32), atol=1e-5)


@pytest.mark.parametrize("pq_bits", [4, 5, 8])
def test_pack_unpack_roundtrip(pq_bits):
    rng = np.random.default_rng(0)
    pq_dim = 16 if pq_bits != 5 else 8 * 5  # pq_dim*pq_bits % 8 == 0
    codes = rng.integers(0, 1 << pq_bits, (64, pq_dim)).astype(np.uint8)
    packed = ivf_pq._pack_codes_np(codes, pq_bits)
    assert packed.shape == (64, pq_dim * pq_bits // 8)
    un = np.asarray(ivf_pq._unpack_codes(jnp.asarray(packed), pq_dim, pq_bits))
    np.testing.assert_array_equal(un, codes)


@pytest.mark.parametrize("kind", [ivf_pq.CodebookGen.PER_SUBSPACE,
                                  ivf_pq.CodebookGen.PER_CLUSTER])
@pytest.mark.slow
def test_recall(data, gt, kind):
    db, q = data
    params = ivf_pq.IndexParams(n_lists=32, pq_dim=16, pq_bits=8,
                                codebook_kind=kind)
    index = ivf_pq.build(db, params)
    d, i = ivf_pq.search(index, q, 10, ivf_pq.SearchParams(n_probes=32))
    recall = float(neighborhood_recall(np.asarray(i), gt))
    # bf16 decoded-scan cache costs ~1e-3 recall vs the f32 LUT path
    assert recall >= 0.79, f"recall {recall} ({kind.name})"
    d32, i32 = ivf_pq.search(
        index, q, 10, ivf_pq.SearchParams(n_probes=32,
                                          scan_cache_dtype=jnp.float32))
    recall32 = float(neighborhood_recall(np.asarray(i32), gt))
    assert recall32 >= 0.8, f"f32-cache recall {recall32} ({kind.name})"


def test_recall_increases_with_probes(data, gt):
    db, q = data
    params = ivf_pq.IndexParams(n_lists=32, pq_dim=16)
    index = ivf_pq.build(db, params)
    recalls = []
    for n_probes in (2, 8, 32):
        _, i = ivf_pq.search(index, q, 10, ivf_pq.SearchParams(n_probes=n_probes))
        recalls.append(float(neighborhood_recall(np.asarray(i), gt)))
    assert recalls[0] <= recalls[1] <= recalls[2] + 0.02
    # full-probe recall = pure ADC quantization quality; the coarse
    # quantizer's balance polish (kmeans_balanced.target_balance_cv)
    # trades a sliver of quantization error for bounded list sizes, so
    # the floor sits just under the historical 0.80
    assert recalls[2] >= 0.77


def test_bf16_lut(data, gt):
    db, q = data
    params = ivf_pq.IndexParams(n_lists=32, pq_dim=16)
    index = ivf_pq.build(db, params)
    sp = ivf_pq.SearchParams(n_probes=32, lut_dtype=jnp.bfloat16,
                             internal_distance_dtype=jnp.float32)
    _, i = ivf_pq.search(index, q, 10, sp)
    assert float(neighborhood_recall(np.asarray(i), gt)) >= 0.75


def test_inner_product(data):
    db, q = data
    dbn = (db / np.linalg.norm(db, axis=1, keepdims=True)).astype(np.float32)
    # pq_len=1 config: validates the IP ADC path with minimal quantization
    # loss (normalized vectors make IP rank gaps tiny — the erfc-model
    # floors in ann_ivf_pq.cuh:164-199 exist for exactly this reason)
    params = ivf_pq.IndexParams(n_lists=16, pq_dim=32,
                                metric="inner_product")
    # pinned seed: the global default Resources' key stream advances with
    # every unseeded build, so recall would depend on test order otherwise
    from raft_tpu import Resources

    index = ivf_pq.build(dbn, params, res=Resources(seed=3))
    _, i = ivf_pq.search(index, q, 10, ivf_pq.SearchParams(n_probes=16))
    ip = q @ dbn.T
    want = np.argsort(-ip, 1)[:, :10]
    assert float(neighborhood_recall(np.asarray(i), want)) >= 0.8


def test_l2sqrt_distances_sqrted(data, res):
    db, q = data
    # identical index state under both metrics (same seed → same build);
    # L2SqrtExpanded distances must be the sqrt of L2Expanded's
    from raft_tpu import Resources

    params = ivf_pq.IndexParams(n_lists=16, pq_dim=16, metric="euclidean")
    index = ivf_pq.build(db, params, res=Resources(seed=7))
    d_sqrt, i1 = ivf_pq.search(index, q, 5, ivf_pq.SearchParams(n_probes=16))
    params2 = ivf_pq.IndexParams(n_lists=16, pq_dim=16, metric="sqeuclidean")
    index2 = ivf_pq.build(db, params2, res=Resources(seed=7))
    d_sq, i2 = ivf_pq.search(index2, q, 5, ivf_pq.SearchParams(n_probes=16))
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
    np.testing.assert_allclose(np.asarray(d_sqrt),
                               np.sqrt(np.maximum(np.asarray(d_sq), 0.0)),
                               rtol=1e-4, atol=1e-4)


def test_extend(data, gt):
    db, q = data
    half = len(db) // 2
    params = ivf_pq.IndexParams(n_lists=32, pq_dim=16)
    index = ivf_pq.build(db[:half], params)
    index = ivf_pq.extend(index, db[half:])
    assert index.size == len(db)
    _, i = ivf_pq.search(index, q, 10, ivf_pq.SearchParams(n_probes=32))
    # codebooks were trained on the first half only → slightly lower floor
    assert float(neighborhood_recall(np.asarray(i), gt)) >= 0.7


def test_device_pack_matches_numpy_pack():
    """_pack_codes_jit (device) must be bit-identical to _pack_codes_np
    (host, shared with the native packers) for every pq_bits."""
    from raft_tpu.neighbors.ivf_pq import _pack_codes_jit, _pack_codes_np

    rng = np.random.default_rng(0)
    for pq_bits in (4, 5, 6, 7, 8):
        pq_dim = 16 if (16 * pq_bits) % 8 == 0 else 8
        codes = rng.integers(0, 1 << pq_bits,
                             (37, pq_dim)).astype(np.uint8)
        got = np.asarray(_pack_codes_jit(jnp.asarray(codes), pq_dim,
                                         pq_bits))
        want = _pack_codes_np(codes, pq_bits)
        np.testing.assert_array_equal(got, want, err_msg=f"bits={pq_bits}")


def test_extend_matches_single_shot_lists(data):
    """Device-side extend must place codes/ids exactly where a from-scratch
    pack of the same rows would (VERDICT r1 #3 gate: list contents identical
    to the host packer's)."""
    db, _ = data
    # a huge expansion budget disables the list cap: both paths must then
    # place every row identically (the capped policy is order-dependent by
    # design and covered by the overflow tests instead)
    params = ivf_pq.IndexParams(n_lists=24, pq_dim=16,
                                add_data_on_build=False,
                                list_pad_expansion=1e9)
    base = ivf_pq.build(db, params)

    # one-shot: everything through the native host packer
    one = ivf_pq.extend(base, db)

    # two-step: first half via the packer, second half via the device
    # scatter (the new path exercised only when lists already exist)
    half = len(db) // 2
    two = ivf_pq.extend(base, db[:half])
    two = ivf_pq.extend(two, db[half:])

    assert two.size == one.size == len(db)
    np.testing.assert_array_equal(np.asarray(one.list_sizes),
                                  np.asarray(two.list_sizes))
    np.testing.assert_array_equal(np.asarray(one.list_indices),
                                  np.asarray(two.list_indices))
    np.testing.assert_array_equal(np.asarray(one.list_codes),
                                  np.asarray(two.list_codes))


@pytest.mark.slow
def test_extend_many_lists_no_per_list_cost():
    """Extend into a many-list index completes without per-list host work
    (the old path paid ~n_lists Python iterations per batch)."""
    import time

    rng = np.random.default_rng(3)
    db = rng.standard_normal((6000, 32)).astype(np.float32)
    params = ivf_pq.IndexParams(n_lists=1500, pq_dim=16,
                                kmeans_n_iters=2, add_data_on_build=True)
    index = ivf_pq.build(db, params)
    more = rng.standard_normal((2000, 32)).astype(np.float32)
    t0 = time.time()
    index = ivf_pq.extend(index, more)
    assert index.size == 8000
    assert time.time() - t0 < 30  # generous CI bound; was minutes-scale


def test_bitset_filter(data):
    db, q = data
    params = ivf_pq.IndexParams(n_lists=16, pq_dim=16)
    index = ivf_pq.build(db, params)
    _, bf_i = brute_force.knn(q, db, k=1, metric="sqeuclidean")
    banned = np.unique(np.asarray(bf_i).ravel())
    filt = Bitset.create(len(db)).set(banned, value=False)
    _, i = ivf_pq.search(index, q, 10, ivf_pq.SearchParams(n_probes=16),
                         filter=filt)
    assert not np.isin(np.asarray(i), banned).any()


def test_serialize_roundtrip(data):
    db, q = data
    params = ivf_pq.IndexParams(n_lists=32, pq_dim=16)
    index = ivf_pq.build(db, params)
    buf = io.BytesIO()
    ivf_pq.serialize(index, buf)
    buf.seek(0)
    index2 = ivf_pq.deserialize(buf)
    d1, i1 = ivf_pq.search(index, q, 10, ivf_pq.SearchParams(n_probes=8))
    d2, i2 = ivf_pq.search(index2, q, 10, ivf_pq.SearchParams(n_probes=8))
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
    np.testing.assert_allclose(np.asarray(d1), np.asarray(d2), rtol=1e-6)


def test_validation():
    with pytest.raises(ValueError, match="pq_bits"):
        ivf_pq.IndexParams(pq_bits=3)
    with pytest.raises(ValueError, match="supports"):
        ivf_pq.IndexParams(metric="cosine")
    rng = np.random.default_rng(0)
    db = rng.standard_normal((100, 32)).astype(np.float32)
    with pytest.raises(ValueError, match="multiple of 8"):
        ivf_pq.build(db, ivf_pq.IndexParams(n_lists=4, pq_dim=10, pq_bits=5))


def test_helpers_codepacker_roundtrip(data):
    db, _ = data
    params = ivf_pq.IndexParams(n_lists=16, pq_dim=16, pq_bits=8,
                                kmeans_n_iters=4)
    index = ivf_pq.build(db, params)
    codes = ivf_pq.helpers.unpack_list_codes(index, 3)
    assert codes.ndim == 2 and codes.shape[1] == 16
    # repack identical codes → index searches the same
    idx2 = ivf_pq.helpers.pack_list_codes(
        index, 3, codes, ids=np.asarray(index.list_indices)[3, :len(codes)])
    np.testing.assert_array_equal(
        np.asarray(idx2.list_codes)[3], np.asarray(index.list_codes)[3])
    # reconstruction approximates member vectors
    rec = ivf_pq.helpers.reconstruct_list_data(index, 3)
    members = np.asarray(index.list_indices)[3, :len(rec)]
    orig = db[members]
    rel = np.linalg.norm(rec - orig) / np.linalg.norm(orig)
    assert rel < 0.5  # coarse: PQ reconstruction error bounded


def test_pallas_scan_path_matches_xla(data):
    """The fused Pallas probe-scan (interpret mode) must agree with the XLA
    gather+einsum cache path."""
    db, q = data
    params = ivf_pq.IndexParams(n_lists=16, pq_dim=16, kmeans_n_iters=4)
    index = ivf_pq.build(db, params)
    ivf_pq.ensure_scan_cache(index)
    empty = jnp.zeros((0,), jnp.uint32)
    args = (jnp.asarray(q[:20]), index.centers, index.rotation,
            index.list_decoded, index.decoded_norms, index.list_indices,
            index.list_sizes, empty, index.metric, 10, 8, 32, False)
    d1, i1 = ivf_pq._search_cache_core(*args)
    d2, i2 = ivf_pq._search_cache_core(*args, use_pallas=True,
                                       pallas_interpret=True)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
    np.testing.assert_allclose(np.asarray(d1), np.asarray(d2),
                               rtol=1e-3, atol=1e-3)


@pytest.mark.parametrize("pq_bits", [4, 5])
def test_low_bit_end_to_end(data, gt, pq_bits):
    """Whole-index build→search at pq_bits<8 (the deep-100M reference config
    uses pq_bits=5 — run/conf/deep-100M.json:252)."""
    db, q = data
    pq_dim = 16 if pq_bits == 4 else 8  # keep pq_dim*pq_bits % 8 == 0
    params = ivf_pq.IndexParams(n_lists=32, pq_dim=pq_dim, pq_bits=pq_bits,
                                kmeans_n_iters=8)
    index = ivf_pq.build(db, params)
    assert index.pq_book_size == 1 << pq_bits
    _, i = ivf_pq.search(index, q, 10, ivf_pq.SearchParams(n_probes=32))
    rec = float(neighborhood_recall(np.asarray(i), gt))
    # fewer bits + coarser codebooks → much lower floor than the 8-bit
    # tests (compression 12.8x / 25.6x; cf. the erfc floor model,
    # ann_ivf_pq.cuh:164-199); measured ~0.52 / ~0.32 on this fixture
    floor = 0.45 if pq_bits == 4 else 0.25
    assert rec >= floor, f"pq_bits={pq_bits} recall {rec}"
    # exact re-rank recovers most of the quantization loss
    from raft_tpu.neighbors import refine as refine_mod

    _, cand = ivf_pq.search(index, q, 30, ivf_pq.SearchParams(n_probes=32))
    _, refined = refine_mod.refine(db, q, np.asarray(cand), 10)
    rec_ref = float(neighborhood_recall(np.asarray(refined), gt))
    assert rec_ref >= rec + 0.1, f"refine didn't recover: {rec}→{rec_ref}"


def test_fp8_lut(data, gt):
    """fp8 LUT (max-abs scaled per subspace, fp_8bit analog) holds recall
    within a few points of the fp32 LUT on the forced-LUT path."""
    from raft_tpu import Resources

    db, q = data
    params = ivf_pq.IndexParams(n_lists=32, pq_dim=16)
    index = ivf_pq.build(db, params, res=Resources(seed=11))
    recalls = {}
    for lut in (jnp.float32, jnp.float8_e4m3fn):
        sp = ivf_pq.SearchParams(n_probes=32, lut_dtype=lut,
                                 scan_mode="lut")
        _, i = ivf_pq.search(index, q, 10, sp)
        recalls[str(lut)] = float(
            neighborhood_recall(np.asarray(i), gt))
    assert recalls["<class 'jax.numpy.float8_e4m3fn'>"] >= \
        recalls["<class 'jax.numpy.float32'>"] - 0.05
    assert recalls["<class 'jax.numpy.float8_e4m3fn'>"] >= 0.7


def test_auto_scan_mode_respects_memory(data):
    """scan_mode='auto' falls back to the LUT engine when the decoded cache
    would not fit the device's memory headroom (DEEP-100M shape analog)."""
    from raft_tpu import Resources

    db, q = data
    params = ivf_pq.IndexParams(n_lists=16, pq_dim=16)
    index = ivf_pq.build(db, params, res=Resources(seed=4))
    # tiny workspace → cache estimate exceeds 4× headroom → LUT engine,
    # which leaves the decoded cache unbuilt
    res = Resources(seed=4, workspace_limit_bytes=1 << 16)
    _, i = ivf_pq.search(index, q, 10, ivf_pq.SearchParams(n_probes=16),
                         res=res)
    assert index.list_decoded is None
    # generous workspace → cache engine builds its decoded slabs
    _, i = ivf_pq.search(index, q, 10, ivf_pq.SearchParams(n_probes=16))
    assert index.list_decoded is not None


def test_scan_mode_auto_is_memory_aware(data):
    """VERDICT r2 #3: "auto" must never materialize a decoded cache the
    device can't afford — the engine choice keys off device/workspace
    memory, and the DEEP-100M flagship shapes resolve to LUT."""
    from raft_tpu import Resources

    # shapes-only: DEEP-100M single-chip (nlist=50000, 1.5x-capped pads
    # for 1e8 rows, rot_dim=96, pq_bits=8, bf16 cache) vs a 16 GB v5e —
    # decoded cache ~29 GB: must pick LUT
    pad = int(1e8 / 50000 * 1.5)
    mode = ivf_pq.resolve_scan_mode(
        n_lists=50000, list_pad=pad, rot_dim=96, n_code_bytes=96,
        cache_itemsize=2, device_memory_bytes=16 << 30,
        workspace_limit_bytes=4 << 30)
    assert mode == "lut"
    # same shapes, 8-chip shard (rows/8): cache fits a 16 GB chip
    mode8 = ivf_pq.resolve_scan_mode(
        n_lists=6250, list_pad=pad, rot_dim=96, n_code_bytes=96,
        cache_itemsize=2, device_memory_bytes=16 << 30,
        workspace_limit_bytes=4 << 30)
    assert mode8 == "cache"

    # end-to-end crossover on a real index: tiny workspace -> LUT (no
    # decoded cache materialized), big workspace -> cache
    db, q = data
    index = ivf_pq.build(db, ivf_pq.IndexParams(n_lists=16, pq_dim=16,
                                                kmeans_n_iters=4))
    lean = Resources(seed=0, workspace_limit_bytes=1 << 10)
    ivf_pq.search(index, q[:8], 5, ivf_pq.SearchParams(n_probes=4),
                  res=lean)
    assert index.list_decoded is None, "auto must not decode under a tiny budget"
    roomy = Resources(seed=0, workspace_limit_bytes=1 << 30)
    ivf_pq.search(index, q[:8], 5, ivf_pq.SearchParams(n_probes=4),
                  res=roomy)
    assert index.list_decoded is not None


@pytest.mark.slow
def test_pq_bits5_end_to_end_both_engines(rng):
    """The DEEP-100M build shape (pq_bits=5, pq_dim=96 → 60 packed
    bytes/row) must build and search on both scan engines with sane
    recall — 5-bit packing is exercised beyond the pack/unpack
    roundtrip (deep-100M.json:252-340 is the chip pareto config)."""
    from raft_tpu.stats import neighborhood_recall

    c = (rng.standard_normal((32, 96)) * 4).astype(np.float32)
    db = (c[rng.integers(0, 32, 20000)]
          + rng.standard_normal((20000, 96))).astype(np.float32)
    q = (c[rng.integers(0, 32, 100)]
         + rng.standard_normal((100, 96))).astype(np.float32)
    gt = np.argsort(((q[:, None, :] - db[None]) ** 2).sum(-1), 1)[:, :10]
    idx = ivf_pq.build(db, ivf_pq.IndexParams(n_lists=64, pq_dim=96,
                                              pq_bits=5))
    for mode in ("lut", "cache"):
        _, i = ivf_pq.search(idx, q, 10,
                             ivf_pq.SearchParams(n_probes=16,
                                                 scan_mode=mode))
        r = float(neighborhood_recall(np.asarray(i), gt))
        assert r > 0.7, (mode, r)


def test_lut_probe_tiling_bit_identical(data):
    """A workspace too small to hold all probes at once forces the
    probe-tile loop (probe_tile < n_probes); the tiled scan must complete
    and return bit-identical values/ids to the untiled single-tile run —
    per-element contractions are unchanged, only the top-k merge order
    differs."""
    from raft_tpu import Resources

    db, q = data
    index = ivf_pq.build(db, ivf_pq.IndexParams(n_lists=32, pq_dim=16),
                         res=Resources(seed=7))
    n_probes = 12
    sp = ivf_pq.SearchParams(n_probes=n_probes, scan_mode="lut")
    v0, i0 = ivf_pq.search(index, q, 10, sp,
                           res=Resources(workspace_limit_bytes=1 << 34))
    list_pad = index.list_codes.shape[1]
    per_qp = ivf_pq.lut_bytes_per_query_probe(list_pad, index.pq_dim,
                                              index.pq_bits)
    tight = Resources(workspace_limit_bytes=per_qp * 8 * 3)
    q_tile, probe_tile = ivf_pq.plan_lut_tiles(
        n_probes, list_pad, index.pq_dim, index.pq_bits,
        tight.workspace_limit_bytes)
    assert probe_tile < n_probes, (q_tile, probe_tile)
    assert q_tile * probe_tile * per_qp <= tight.workspace_limit_bytes
    v1, i1 = ivf_pq.search(index, q, 10, sp, res=tight)
    np.testing.assert_array_equal(np.asarray(v0), np.asarray(v1))
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))


def test_lut_probe_tiling_matches_cache_engine(data, gt):
    """Tiled-LUT results stay within the existing lut-vs-cache parity
    tolerance: both engines compute the same ADC distances (fp32 LUT vs
    fp32 decoded cache differ only in accumulation order), so where the
    returned ids agree the distances agree to float tolerance, the
    neighbor sets overlap almost entirely (near-tie rank swaps only),
    and recall holds the same floor."""
    from raft_tpu import Resources

    db, q = data
    index = ivf_pq.build(db, ivf_pq.IndexParams(n_lists=32, pq_dim=16),
                         res=Resources(seed=7))
    n_probes = 12
    list_pad = index.list_codes.shape[1]
    per_qp = ivf_pq.lut_bytes_per_query_probe(list_pad, index.pq_dim,
                                              index.pq_bits)
    tight = Resources(workspace_limit_bytes=per_qp * 8 * 3)
    # scan_cache_dtype also governs the overflow-block decode on the lut
    # path — hold it at fp32 on BOTH engines so spilled rows don't drift
    v1, i1 = ivf_pq.search(
        index, q, 10, ivf_pq.SearchParams(n_probes=n_probes,
                                          scan_mode="lut",
                                          scan_cache_dtype=jnp.float32),
        res=tight)
    vc, ic = ivf_pq.search(
        index, q, 10, ivf_pq.SearchParams(n_probes=n_probes,
                                          scan_mode="cache",
                                          scan_cache_dtype=jnp.float32))
    v1, i1, vc, ic = map(np.asarray, (v1, i1, vc, ic))
    same = i1 == ic
    assert same.mean() >= 0.95, same.mean()
    np.testing.assert_allclose(v1[same], vc[same], rtol=1e-4, atol=1e-3)
    overlap = np.mean([len(np.intersect1d(a, b)) / 10.0
                       for a, b in zip(i1, ic)])
    assert overlap >= 0.97, overlap
    r_lut = float(neighborhood_recall(i1, gt))
    r_cache = float(neighborhood_recall(ic, gt))
    assert r_lut >= r_cache - 0.02 and r_lut >= 0.7, (r_lut, r_cache)


def test_resolve_scan_mode_lut_at_1m_shape_with_fitting_tiles():
    """The sift-1M crash shape (LUT_CRASH_tpu.json: nlist=1024, ~1464
    list pad, pq_dim=64, pq_bits=8, nprobe=64): when the decoded cache
    does not fit the headroom, auto resolves to LUT — which is now safe
    because plan_lut_tiles bounds the scan workspace by construction
    (the old one-axis solve under-counted the live set ~5x and sized
    q_tile=136 -> ~19 GB on a 16 GB chip)."""
    list_pad, pq_dim, pq_bits, n_probes = 1464, 64, 8, 64
    # fp32 cache at this shape ~ 774 MB on top of ~102 MB packed; a
    # 512 MB headroom (no reported device memory, 128 MB workspace x4)
    # cannot hold it -> LUT
    mode = ivf_pq.resolve_scan_mode(
        n_lists=1024, list_pad=list_pad, rot_dim=128, n_code_bytes=64,
        cache_itemsize=4, device_memory_bytes=None,
        workspace_limit_bytes=128 << 20)
    assert mode == "lut"
    q_tile, probe_tile = ivf_pq.plan_lut_tiles(
        n_probes, list_pad, pq_dim, pq_bits, 128 << 20)
    per_qp = ivf_pq.lut_bytes_per_query_probe(list_pad, pq_dim, pq_bits)
    assert q_tile >= 1 and 1 <= probe_tile <= n_probes
    assert q_tile * probe_tile * per_qp <= 128 << 20
    # the crash accounting: at the old q_tile=136 with all 64 probes the
    # true live set was multiple device memories — the joint solve must
    # never produce it under ANY budget that reports the 16 GB chip
    q16, p16 = ivf_pq.plan_lut_tiles(n_probes, list_pad, pq_dim, pq_bits,
                                     (16 << 30) // 4)
    assert q16 * p16 * per_qp <= (16 << 30) // 4
