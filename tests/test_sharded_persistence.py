"""Sharded index checkpoint/resume (the raft-dask per-worker persistence
role): rank files round-trip both engines bit-exactly on the virtual
8-device mesh."""

import numpy as np
import pytest

from raft_tpu.core.resources import Resources
from raft_tpu.neighbors import ivf_flat, ivf_pq
from raft_tpu.parallel import comms as comms_mod
from raft_tpu.parallel import sharded


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(5)
    centers = (rng.standard_normal((32, 32)) * 4).astype(np.float32)
    x = (centers[rng.integers(0, 32, 4096)]
         + rng.standard_normal((4096, 32))).astype(np.float32)
    q = (centers[rng.integers(0, 32, 32)]
         + rng.standard_normal((32, 32))).astype(np.float32)
    return x, q


@pytest.mark.parametrize("scan_mode", ["lut", "cache"])
def test_sharded_ivf_pq_roundtrip(tmp_path, data, scan_mode):
    x, q = data
    comms = comms_mod.init_comms(axis="persist_pq_" + scan_mode)
    idx = sharded.build_ivf_pq(
        comms, x, ivf_pq.IndexParams(n_lists=16, pq_dim=16,
                                     kmeans_n_iters=3),
        res=Resources(seed=0), scan_mode=scan_mode)
    d0, i0 = sharded.search_ivf_pq(idx, q, 10,
                                   ivf_pq.SearchParams(n_probes=8))
    prefix = str(tmp_path / f"pq_{scan_mode}")
    sharded.serialize_ivf_pq(idx, prefix)
    idx2 = sharded.deserialize_ivf_pq(prefix, comms)
    d1, i1 = sharded.search_ivf_pq(idx2, q, 10,
                                   ivf_pq.SearchParams(n_probes=8))
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
    np.testing.assert_allclose(np.asarray(d0), np.asarray(d1), rtol=1e-6)


def test_sharded_ivf_flat_roundtrip(tmp_path, data):
    x, q = data
    comms = comms_mod.init_comms(axis="persist_flat")
    idx = sharded.build_ivf_flat(
        comms, x, ivf_flat.IndexParams(n_lists=16, kmeans_n_iters=3),
        res=Resources(seed=0))
    d0, i0 = sharded.search_ivf_flat(idx, q, 10,
                                     ivf_flat.SearchParams(n_probes=8))
    prefix = str(tmp_path / "flat")
    sharded.serialize_ivf_flat(idx, prefix)
    idx2 = sharded.deserialize_ivf_flat(prefix, comms)
    d1, i1 = sharded.search_ivf_flat(idx2, q, 10,
                                     ivf_flat.SearchParams(n_probes=8))
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
    np.testing.assert_allclose(np.asarray(d0), np.asarray(d1), rtol=1e-6)


def test_sharded_deserialize_validation(tmp_path, data):
    import shutil

    import jax

    x, _ = data
    comms = comms_mod.init_comms(axis="persist_mismatch")
    idx = sharded.build_ivf_flat(
        comms, x, ivf_flat.IndexParams(n_lists=8, kmeans_n_iters=2),
        res=Resources(seed=0))
    prefix = str(tmp_path / "mm")
    sharded.serialize_ivf_flat(idx, prefix)

    # a comms of a different size must be rejected
    comms4 = comms_mod.init_comms(jax.devices()[:4], axis="persist_mm4")
    with pytest.raises(ValueError, match="sharded over"):
        sharded.deserialize_ivf_flat(prefix, comms4)

    # a stale rank file from a previous layout (duplicate shard ranks)
    # must be rejected rather than silently merged
    shutil.copy(prefix + ".rank0", prefix + ".rank1")
    with pytest.raises(ValueError, match="stale rank files"):
        sharded.deserialize_ivf_flat(prefix, comms)

    # a partial checkpoint (missing shard ranks) must name the gap AND the
    # expected file paths the operator should go look for
    with pytest.raises(ValueError,
                       match=r"missing \[1, 3\].*p\.rank1, p\.rank3"):
        sharded._check_rank_coverage({0: "f", 2: "f"}, 4, "p")

    import os

    # dropping some (not all) rank files is a coverage error naming them
    os.remove(prefix + ".rank1")
    os.remove(prefix + ".rank0")
    with pytest.raises(ValueError, match=r"missing \[0, 1\]"):
        sharded.deserialize_ivf_flat(prefix, comms)

    # and a prefix with no rank files at all fails loudly
    for p in os.listdir(tmp_path):
        if p.startswith("mm.rank"):
            os.remove(tmp_path / p)
    with pytest.raises(FileNotFoundError):
        sharded.deserialize_ivf_flat(prefix, comms)


def _assert_same_neighbors(d0, i0, d1, i1, rtol=1e-4):
    """Mesh and elastic searches run the same cores compiled differently
    (shard_map vs lax.map), so distances agree only to fp tolerance and a
    near-tie at the k-th cut may legitimately flip ids. Assert distance
    closeness plus per-row id agreement allowing one boundary flip."""
    d0, i0 = np.asarray(d0), np.asarray(i0)
    d1, i1 = np.asarray(d1), np.asarray(i1)
    np.testing.assert_allclose(d0, d1, rtol=rtol)
    k = i0.shape[1]
    for r, (a, b) in enumerate(zip(i0, i1)):
        assert len(set(a) & set(b)) >= k - 1, (r, a, b)


@pytest.mark.parametrize("scan_mode", ["lut", "cache"])
def test_elastic_restore_matches_mesh_search(tmp_path, data, scan_mode):
    """Elastic restore (any device count) returns the same neighbors as
    the mesh search it was checkpointed from (distances to fp tolerance —
    same cores, same merge, different compiled program), no mesh required
    (the single-chip serving path for a multi-shard
    build)."""
    x, q = data
    comms = comms_mod.init_comms(axis="elastic_pq_" + scan_mode)
    idx = sharded.build_ivf_pq(
        comms, x, ivf_pq.IndexParams(n_lists=16, pq_dim=16,
                                     kmeans_n_iters=3),
        res=Resources(seed=0), scan_mode=scan_mode)
    sp = ivf_pq.SearchParams(n_probes=8)
    d0, i0 = sharded.search_ivf_pq(idx, q, 10, sp)
    prefix = str(tmp_path / f"el_{scan_mode}")
    sharded.serialize_ivf_pq(idx, prefix)

    el = sharded.deserialize_ivf_pq_elastic(prefix)
    assert el.n_shards == comms.size
    d1, i1 = el.search(q, 10, sp)
    _assert_same_neighbors(d0, i0, d1, i1)

    # recall floor vs the exact oracle (not just self-consistency)
    from raft_tpu.neighbors import brute_force
    from raft_tpu.stats import neighborhood_recall

    _, gt = brute_force.knn(q, x, k=10, metric="sqeuclidean")
    rec = float(neighborhood_recall(np.asarray(i1), np.asarray(gt)))
    assert rec >= 0.8, rec


def test_elastic_restore_with_overflow(tmp_path):
    """Spilled rows (overflow blocks) survive elastic restore: force tiny
    padded lists so some rows overflow, and require the elastic search to
    still find them."""
    rng = np.random.default_rng(9)
    # one heavy cluster: most rows land in few lists -> list_pad caps and
    # rows spill to the overflow block
    x = np.concatenate([
        rng.standard_normal((3000, 16)).astype(np.float32) * 0.05,
        rng.standard_normal((1096, 16)).astype(np.float32) + 8.0,
    ])
    q = x[:24] + rng.standard_normal((24, 16)).astype(np.float32) * 0.01
    comms = comms_mod.init_comms(axis="elastic_over")
    idx = sharded.build_ivf_pq(
        comms, x, ivf_pq.IndexParams(n_lists=32, pq_dim=8,
                                     kmeans_n_iters=3),
        res=Resources(seed=0), scan_mode="lut")
    sp = ivf_pq.SearchParams(n_probes=32)
    d0, i0 = sharded.search_ivf_pq(idx, q, 10, sp)
    prefix = str(tmp_path / "el_over")
    sharded.serialize_ivf_pq(idx, prefix)
    el = sharded.deserialize_ivf_pq_elastic(prefix)
    d1, i1 = el.search(q, 10, sp)
    _assert_same_neighbors(d0, i0, d1, i1)
