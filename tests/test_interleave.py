"""Seeded schedule amplification (raft_tpu.testing.interleave).

Fast tier: the amplifier's mechanics (seed plumbing, guarded-field
discovery, state restoration). Slow ``interleave`` tier: the T001
fixture twins actually race/stay-exact under amplified preemption (the
"fixture flips racy-fail -> pass when its flagged code is fixed"
evidence for the analyzer), and the serving engine keeps its
zero-dropped / zero-duplicated futures contract across 200 seeds."""
import importlib.util
import os
import sys
import threading

import numpy as np
import pytest

from raft_tpu.testing.interleave import (ENV_SEED, InterleaveAmplifier,
                                         env_seed, guarded_fields, seeds)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXDIR = os.path.join(REPO, "tests", "data", "graftcheck")


def _load_fixture(fname, modname):
    spec = importlib.util.spec_from_file_location(
        modname, os.path.join(FIXDIR, fname))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------------------- fast tier

def test_env_seed_reads_environment(monkeypatch):
    monkeypatch.delenv(ENV_SEED, raising=False)
    assert env_seed() == 0
    assert env_seed(7) == 7
    monkeypatch.setenv(ENV_SEED, "41")
    assert env_seed() == 41
    monkeypatch.setenv(ENV_SEED, "not-an-int")
    assert env_seed(3) == 3


def test_seeds_sweep_is_anchored_and_distinct(monkeypatch):
    monkeypatch.setenv(ENV_SEED, "100")
    assert seeds(3) == [100, 101, 102]
    assert seeds(2, base=7) == [7, 8]


def test_guarded_fields_discovers_annotations():
    fields = guarded_fields(
        os.path.join(REPO, "raft_tpu", "serving", "batcher.py"))
    assert "_queue" in fields and "_stopping" in fields


def test_amplifier_restores_interpreter_state():
    before_interval = sys.getswitchinterval()
    with InterleaveAmplifier(seed=1, path_filters=("nothing-matches",)):
        assert sys.getswitchinterval() != before_interval
    assert sys.getswitchinterval() == before_interval
    assert sys.gettrace() is None


# ------------------------------------------- fixture twins actually race

def _run_counter(counter_cls, seed, n=400, threads=2):
    c = counter_cls()
    with InterleaveAmplifier(seed=seed, yield_probability=0.2,
                             path_filters=("t001_",)):
        ts = [threading.Thread(target=c.add, args=(n,))
              for _ in range(threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    return c.count, n * threads


@pytest.mark.slow
@pytest.mark.interleave
def test_t001_bad_fixture_races_under_amplifier():
    """The code T001 flags demonstrably loses updates under amplified
    preemption — within a handful of seeds, never needing luck."""
    mod = _load_fixture("t001_bad.py", "t001_bad_runtime")
    for seed in seeds(10):
        got, want = _run_counter(mod.SharedCounter, seed)
        if got != want:
            return  # racy, as the finding claims
    pytest.fail("t001_bad.SharedCounter never lost an increment "
                "across 10 amplified seeds")


@pytest.mark.slow
@pytest.mark.interleave
def test_t001_clean_fixture_exact_under_amplifier():
    """...and the fixed twin (the clean fixture) stays exact under the
    same amplification: the racy-fail flips to pass."""
    mod = _load_fixture("t001_clean.py", "t001_clean_runtime")
    for seed in seeds(5):
        got, want = _run_counter(mod.SharedCounter, seed)
        assert got == want, f"seed {seed}: {got} != {want}"


# --------------------------------- serving engine under amplified seeds

def _fake_searcher(dim=8):
    """Pure-numpy Searcher duck-type: no JAX compile per seed, instant
    'device' results, so 200 amplified engine lifecycles stay cheap."""
    from types import SimpleNamespace

    from raft_tpu.serving.searchers import Searcher

    def search(batch, k):
        n = batch.shape[0]
        d = np.tile(np.arange(k, dtype=np.float32), (n, 1))
        i = np.tile(np.arange(k, dtype=np.int64), (n, 1))
        return d, i

    return Searcher("fake", dim, SimpleNamespace(), search)


@pytest.mark.slow
@pytest.mark.interleave
def test_engine_no_dropped_or_duplicated_futures_across_seeds():
    """The chaos contract under amplified preemption: every submitted
    future resolves exactly once (done, correct row shape), across 200
    interleaving seeds with 3 concurrent submitters."""
    from raft_tpu.obs import metrics as obs_metrics
    from raft_tpu.serving.engine import Engine, EngineConfig

    K, DIM, PER_THREAD, SUBMITTERS = 5, 8, 5, 3
    fields = guarded_fields(
        os.path.join(REPO, "raft_tpu", "serving", "engine.py"))
    for seed in seeds(200):
        cfg = EngineConfig(max_batch=4, max_wait_us=300,
                           warm_ks=(K,), warm_buckets=(1, 4),
                           persistent_cache=False, hang_timeout_s=None,
                           flight_recorder=False,
                           registry=obs_metrics.Registry())
        engine = Engine(_fake_searcher(DIM), cfg)
        futures = []
        fut_lock = threading.Lock()

        def submitter(eng=engine):
            rng = np.random.default_rng(0)
            for _ in range(PER_THREAD):
                f = eng.submit(
                    rng.standard_normal(DIM).astype(np.float32), K)
                with fut_lock:
                    futures.append(f)

        with InterleaveAmplifier(
                seed=seed, yield_probability=0.05,
                path_filters=("raft_tpu",), fields=fields):
            engine.start()
            ts = [threading.Thread(target=submitter)
                  for _ in range(SUBMITTERS)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            engine.stop(drain=True)

        assert len(futures) == PER_THREAD * SUBMITTERS, seed
        for f in futures:  # resolved exactly once, with a real row
            assert f.done(), f"seed {seed}: future never resolved"
            d, i = f.result(timeout=0)
            assert d.shape == (K,) and i.shape == (K,), seed
        stats = engine.stats
        assert stats.n_submitted == PER_THREAD * SUBMITTERS, seed
        assert stats.n_completed == PER_THREAD * SUBMITTERS, seed
        assert stats.n_failed == 0 and stats.n_cancelled == 0, seed
