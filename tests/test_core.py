"""Core-layer tests: Resources, bitset, serialization, fused L2 NN, RNG,
stats."""

import io

import numpy as np
import pytest

from raft_tpu import Resources
from raft_tpu.core import Bitset, serialize as ser
from raft_tpu.ops import fused_l2_nn_argmin
from raft_tpu.ops import rng as rrng
from raft_tpu import stats


class TestResources:
    def test_keys_unique(self):
        res = Resources(seed=1)
        import jax
        k1, k2 = res.next_key(), res.next_key()
        assert not np.array_equal(
            np.asarray(jax.random.key_data(k1)), np.asarray(jax.random.key_data(k2))
        )

    def test_custom_slot(self):
        res = Resources()
        calls = []
        res.get_resource("x", lambda: calls.append(1) or 42)
        v = res.get_resource("x", lambda: calls.append(1) or 43)
        assert v == 42 and len(calls) == 1

    def test_comms_unset_raises(self):
        with pytest.raises(RuntimeError):
            Resources().comms


class TestBitset:
    def test_create_default_all_set(self):
        b = Bitset.create(70)
        assert int(b.count()) == 70

    def test_set_test_flip(self):
        b = Bitset.create(100, default=False)
        b = b.set(np.array([0, 31, 32, 99, 99]))
        assert int(b.count()) == 4
        got = np.asarray(b.test(np.array([0, 1, 31, 32, 99])))
        np.testing.assert_array_equal(got, [True, False, True, True, True])
        f = b.flip()
        assert int(f.count()) == 96

    def test_clear(self):
        b = Bitset.create(64).set(np.array([3, 5]), value=False)
        assert int(b.count()) == 62

    def test_mask_roundtrip(self, rng):
        mask = rng.random(130) > 0.5
        b = Bitset.from_mask(mask)
        np.testing.assert_array_equal(np.asarray(b.to_mask()), mask)
        assert int(b.count()) == mask.sum()


class TestSerialize:
    def test_scalar_array_roundtrip(self, rng):
        buf = io.BytesIO()
        a = rng.standard_normal((3, 4)).astype(np.float32)
        ser.serialize_scalar(buf, 42, "<i8")
        ser.serialize_array(buf, a)
        ser.serialize_scalar(buf, 2.5, "<f4")
        buf.seek(0)
        assert ser.deserialize_scalar(buf) == 42
        np.testing.assert_array_equal(ser.deserialize_array(buf), a)
        assert ser.deserialize_scalar(buf) == 2.5

    def test_npy_compatible(self, rng):
        """Arrays are raw .npy records — numpy can read them directly,
        matching the reference's interchange guarantee (core/serialize.hpp)."""
        buf = io.BytesIO()
        a = (rng.standard_normal((5, 2)) * 10).astype(np.int32)
        ser.serialize_array(buf, a)
        buf.seek(0)
        np.testing.assert_array_equal(np.load(buf), a)

    def test_kind_mismatch(self):
        buf = io.BytesIO()
        ser.IndexWriter(buf, "ivf_flat", 1)
        buf.seek(0)
        with pytest.raises(ValueError, match="kind mismatch"):
            ser.IndexReader(buf, "ivf_pq", 1)


class TestFusedL2NN:
    def test_matches_naive(self, rng):
        x = rng.standard_normal((300, 17)).astype(np.float32)
        y = rng.standard_normal((37, 17)).astype(np.float32)
        val, idx = fused_l2_nn_argmin(x, y)
        d = ((x[:, None, :] - y[None, :, :]) ** 2).sum(-1)
        np.testing.assert_array_equal(np.asarray(idx), d.argmin(1))
        np.testing.assert_allclose(np.asarray(val), d.min(1), rtol=1e-3, atol=1e-3)

    def test_tiled(self, rng):
        x = rng.standard_normal((1000, 8)).astype(np.float32)
        y = rng.standard_normal((16, 8)).astype(np.float32)
        small = Resources(workspace_limit_bytes=100_000)
        val, idx = fused_l2_nn_argmin(x, y, sqrt=True, res=small)
        d = np.sqrt(((x[:, None, :] - y[None, :, :]) ** 2).sum(-1))
        np.testing.assert_array_equal(np.asarray(idx), d.argmin(1))
        np.testing.assert_allclose(np.asarray(val), d.min(1), rtol=1e-3, atol=1e-3)


class TestRng:
    def test_make_blobs_separable(self):
        x, labels, centers = rrng.make_blobs(
            0, 1000, 8, n_clusters=4, cluster_std=0.1, return_centers=True
        )
        x, labels, centers = map(np.asarray, (x, labels, centers))
        # every point is closest to its own center
        d = ((x[:, None, :] - centers[None]) ** 2).sum(-1)
        assert (d.argmin(1) == labels).mean() > 0.999

    def test_sample_without_replacement(self):
        s = np.asarray(rrng.sample_without_replacement(0, 100, 50))
        assert len(np.unique(s)) == 50 and s.max() < 100

    def test_permute(self):
        p = np.asarray(rrng.permute(0, 64))
        assert sorted(p) == list(range(64))

    def test_rng_state(self):
        import jax
        k1 = rrng.RngState(1, 0).key()
        k2 = rrng.RngState(1, 1).key()
        assert not np.array_equal(
            np.asarray(jax.random.key_data(k1)), np.asarray(jax.random.key_data(k2))
        )

    def test_rmat_shape(self):
        edges = np.asarray(rrng.rmat(0, r_scale=4, c_scale=3, n_edges=100))
        assert edges.shape == (100, 2)
        assert edges[:, 0].max() < 16 and edges[:, 1].max() < 8
        assert edges.min() >= 0

    def test_make_regression(self):
        x, y, coef = rrng.make_regression(0, 200, 5, noise=0.0)
        np.testing.assert_allclose(
            np.asarray(y), np.asarray(x) @ np.asarray(coef), rtol=1e-3, atol=1e-2
        )


class TestStats:
    def test_neighborhood_recall(self):
        got = np.array([[1, 2, 3], [4, 5, 6]])
        ref = np.array([[1, 2, 9], [4, 5, 6]])
        assert float(stats.neighborhood_recall(got, ref)) == pytest.approx(5 / 6)

    def test_vs_sklearn_cluster_metrics(self, rng):
        from sklearn import metrics as skm

        a = rng.integers(0, 4, 200)
        b = rng.integers(0, 3, 200)
        assert float(stats.adjusted_rand_index(a, b, 4, 3)) == pytest.approx(
            skm.adjusted_rand_score(a, b), abs=1e-4
        )
        assert float(stats.mutual_info_score(a, b, 4, 3)) == pytest.approx(
            skm.mutual_info_score(a, b), abs=1e-4
        )
        assert float(stats.v_measure(a, b, 4, 3)) == pytest.approx(
            skm.v_measure_score(a, b), abs=1e-4
        )

    def test_silhouette_vs_sklearn(self, rng):
        from sklearn import metrics as skm

        x = rng.standard_normal((100, 4)).astype(np.float32)
        labels = rng.integers(0, 3, 100)
        got = float(stats.silhouette_score(x, labels, 3, metric="l2sqrt_expanded"))
        want = skm.silhouette_score(x, labels, metric="euclidean")
        assert got == pytest.approx(want, abs=1e-3)

    def test_histogram(self, rng):
        x = rng.standard_normal(1000).astype(np.float32)
        counts, edges = stats.histogram(x, 10)
        want, _ = np.histogram(x, bins=np.asarray(edges))
        assert int(np.asarray(counts).sum()) == 1000
        np.testing.assert_allclose(np.asarray(counts), want, atol=1)

    def test_r2(self, rng):
        y = rng.standard_normal(50)
        assert float(stats.r2_score(y, y)) == pytest.approx(1.0)


class TestSolveJointTiles:
    """solve_joint_tiles: the workspace-bounded (outer, inner) loop-nest
    solve behind ivf_pq.plan_lut_tiles."""

    def test_full_inner_preferred(self):
        from raft_tpu.core.resources import solve_joint_tiles
        # 100 cells' worth of budget, inner extent 4 -> outer 24 (8-aligned)
        outer, inner = solve_joint_tiles(100 * 10, 10, 4)
        assert (outer, inner) == (24, 4)

    def test_outer_capped(self):
        from raft_tpu.core.resources import solve_joint_tiles
        outer, inner = solve_joint_tiles(10_000 * 10, 10, 4, outer_cap=256)
        assert (outer, inner) == (256, 4)

    def test_inner_shrinks_when_full_extent_oversized(self):
        from raft_tpu.core.resources import solve_joint_tiles
        # full inner extent (64) would need 8*64=512 cells; budget holds
        # only 8*3 -> keep the lane-aligned outer=8, tile the inner loop
        outer, inner = solve_joint_tiles(8 * 3 * 10, 10, 64)
        assert (outer, inner) == (8, 3)

    def test_degrades_to_single_cell(self):
        from raft_tpu.core.resources import solve_joint_tiles
        # one cell exceeds the budget: (1, 1), never (0, _)
        outer, inner = solve_joint_tiles(5, 10, 64)
        assert (outer, inner) == (1, 1)
