"""Opt-in runtime-check harness (SURVEY.md §5 sanitizer analog)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_tpu.utils import debug


def test_checked_passes_clean_function():
    f = debug.checked(jax.jit(lambda x: jnp.sqrt(x) + 1.0))
    out = f(jnp.ones((4,)))
    np.testing.assert_allclose(np.asarray(out), 2.0)


def test_checked_catches_nan():
    f = debug.checked(jax.jit(lambda x: jnp.log(x)))
    with pytest.raises(Exception, match="nan"):
        f(jnp.array([-1.0]))


def test_checked_catches_oob_gather():
    f = debug.checked(jax.jit(lambda x, i: x[i]))
    with pytest.raises(Exception, match="out-of-bounds|index"):
        f(jnp.arange(4.0), jnp.array([7]))


def test_checked_on_library_search():
    """The harness composes with real library entry points."""
    from raft_tpu.neighbors import brute_force

    rng = np.random.default_rng(0)
    db = rng.standard_normal((200, 8)).astype(np.float32)
    q = rng.standard_normal((10, 8)).astype(np.float32)
    index = brute_force.build(db, metric="sqeuclidean")
    d, i = debug.checked(lambda qq: brute_force.search(index, qq, 5))(q)
    _, want = brute_force.search(index, q, 5)
    np.testing.assert_array_equal(np.asarray(i), np.asarray(want))


def test_debug_mode_restores_flags():
    before = jax.config.jax_debug_nans
    with debug.debug_mode():
        assert jax.config.jax_debug_nans
    assert jax.config.jax_debug_nans == before
