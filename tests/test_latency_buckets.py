"""Small-batch serving path (VERDICT r2 #7 — the reference ships
MULTI_CTA/MULTI_KERNEL CAGRA modes for 1-10-query serving,
cagra_types.hpp:66-116; on TPU the per-shape XLA recompile is what kills
small-batch latency, so searches round small batches up to power-of-two
buckets and reuse one compiled program)."""

import numpy as np
import pytest

from raft_tpu import Resources
from raft_tpu.neighbors import brute_force, cagra, ivf_flat, ivf_pq
from raft_tpu.utils.shape import query_bucket


def test_query_bucket_shape():
    assert [query_bucket(n) for n in (1, 7, 8, 9, 100, 256, 257, 10000)] \
        == [8, 8, 8, 16, 128, 256, 257, 10000]


@pytest.fixture(scope="module")
def setup():
    rng = np.random.default_rng(0)
    centers = rng.standard_normal((16, 24)) * 4.0
    db = (centers[rng.integers(0, 16, 2000)]
          + rng.standard_normal((2000, 24))).astype(np.float32)
    q = (centers[rng.integers(0, 16, 64)]
         + rng.standard_normal((64, 24))).astype(np.float32)
    return db, q


def test_small_batches_agree_across_bucket_sizes(setup):
    """A query's result must not depend on which batch it arrived in:
    batch 1, 3, and 64 runs of the same query return identical neighbors
    (per-query independence; padding rows are sliced off)."""
    db, q = setup
    res = Resources(seed=0)
    bf = brute_force.build(db, metric="sqeuclidean")
    fl = ivf_flat.build(db, ivf_flat.IndexParams(n_lists=8), res=res)
    pq = ivf_pq.build(db, ivf_pq.IndexParams(n_lists=8, pq_dim=8,
                                             kmeans_n_iters=4), res=res)
    sp_fl = ivf_flat.SearchParams(n_probes=8)
    sp_pq = ivf_pq.SearchParams(n_probes=8)
    for name, fn in [
        ("brute_force", lambda qq: brute_force.search(bf, qq, 5)),
        ("ivf_flat", lambda qq: ivf_flat.search(fl, qq, 5, sp_fl)),
        ("ivf_pq", lambda qq: ivf_pq.search(pq, qq, 5, sp_pq)),
    ]:
        d_full, i_full = fn(q)
        assert d_full.shape == (64, 5), name
        for b in (1, 3, 10):
            d_b, i_b = fn(q[:b])
            assert d_b.shape == (b, 5), (name, b)
            np.testing.assert_array_equal(
                np.asarray(i_b), np.asarray(i_full)[:b],
                err_msg=f"{name} batch {b}")
            np.testing.assert_allclose(
                np.asarray(d_b), np.asarray(d_full)[:b], rtol=1e-5,
                atol=1e-5, err_msg=f"{name} batch {b}")


def test_cagra_small_batch_shapes_and_recall(setup):
    """CAGRA's seed lattice is batch-size independent (row q's seeds
    depend only on q), so small batches hit the same per-query recall
    as large ones — 16 well-separated clusters make the kNN graph
    disconnected, and the stratified lattice seeds every component."""
    db, q = setup
    res = Resources(seed=0)
    cg = cagra.build(db, cagra.IndexParams(graph_degree=16,
                                           intermediate_graph_degree=32),
                     res=res)
    _, gt = brute_force.knn(q[:10], db, k=5, metric="sqeuclidean")
    from raft_tpu.stats import neighborhood_recall

    for b in (1, 3, 10):
        d, i = cagra.search(cg, q[:b], 5,
                            cagra.SearchParams(itopk_size=32))
        assert d.shape == (b, 5)
        r = float(neighborhood_recall(np.asarray(i),
                                      np.asarray(gt)[:b]))
        assert r >= 0.85, (b, r)


def test_bucketing_reuses_compiled_programs(setup):
    """Batches 1..8 share the 8-bucket program: after one warm call at
    batch 8, batches 1-7 must not trigger a fresh trace of the search
    core (counted via the jit cache)."""
    db, q = setup
    res = Resources(seed=0)
    fl = ivf_flat.build(db, ivf_flat.IndexParams(n_lists=8), res=res)
    sp = ivf_flat.SearchParams(n_probes=8)
    from raft_tpu.neighbors.ivf_flat import _search_jit

    ivf_flat.search(fl, q[:8], 5, sp)  # warm the 8-bucket
    misses0 = _search_jit._cache_size()
    for b in (1, 2, 3, 5, 7, 8):
        ivf_flat.search(fl, q[:b], 5, sp)
    assert _search_jit._cache_size() == misses0, \
        "small batches must reuse the bucket's compiled program"
