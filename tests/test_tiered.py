"""HBM-as-cache tiered serving (raft_tpu/neighbors/tiered.py).

The load-bearing claims, each pinned here:

- **Bit identity** — `TieredIvfPq.search` equals the all-HBM ivf_pq
  cache engine (`scan_mode="cache"`) bit-for-bit at multiple shapes,
  including a ragged last list and an overflow block, across metrics
  and codebook kinds — through misses, hits, and LRU eviction churn.
- **Zero compiles on the steady-state hit path** — after one warmed
  search, repeat searches compile nothing (`serving.compile_count()`
  delta 0).
- **`Batcher.peek()` is advisory** — non-consuming, and deadline
  pruning behaves identically whether or not anyone peeked.
- **Telemetry reconciles 1:1** — the arena's registry counters equal
  its own `snapshot_counts()`, fetch spans carry the requesting trace
  id, and every metric name is documented in docs/observability.md.
- **Races stay exact** — amplified interleavings of concurrent search
  + prefetch + eviction keep the counter identities exact per seed
  (hits + misses + prefetch_hits + prefetch_fetches == resolved;
  inserts == misses + prefetch_fetches; evictions == inserts −
  occupancy) and the results bit-identical.
- **Degraded path is typed** — a host-tier read failure surfaces as
  `BatchFailed` with `__cause__` `TierReadError`, never a hang.
- **CPU smoke** — an index ≥4x the arena served through the engine
  under the deadline policy: hit-rate < 1.0, nonzero useful
  prefetches, zero untyped failures, and `solve_host_tier` exact on
  arena/host bytes.
"""

import json
import os
import threading
from concurrent.futures import Future

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import Resources, serving
from raft_tpu.core.resources import solve_host_tier
from raft_tpu.neighbors import ivf_pq, tiered
from raft_tpu.neighbors.ivf_pq import (CodebookGen, DistanceType,
                                       IndexParams, SearchParams)
from raft_tpu.obs import metrics as obs_metrics
from raft_tpu.obs import spans as obs_spans
from raft_tpu.serving import BatchFailed
from raft_tpu.serving.batcher import Batcher, Request
from raft_tpu.testing.interleave import (InterleaveAmplifier,
                                         guarded_fields, seeds)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _bits(a):
    return np.asarray(a).view(np.uint32)


def _build(rows=900, dim=24, n_lists=37, pq_dim=12, seed=0,
           metric=DistanceType.L2Expanded,
           codebook_kind=CodebookGen.PER_SUBSPACE, res=None):
    rng = np.random.default_rng(seed)
    db = rng.standard_normal((rows, dim), dtype=np.float32)
    idx = ivf_pq.build(db, IndexParams(
        n_lists=n_lists, pq_dim=pq_dim, metric=metric,
        codebook_kind=codebook_kind, kmeans_n_iters=4),
        res=res or Resources(seed=0))
    return db, idx


def _assert_identical(t, idx, queries, k, params, res):
    vt, it = t.search(queries, k, params, res=res)
    vr, ir = ivf_pq.search(idx, queries, k, params, res=res)
    np.testing.assert_array_equal(np.asarray(it), np.asarray(ir))
    np.testing.assert_array_equal(_bits(vt), _bits(vr))


# ------------------------------------------------------------ bit identity


@pytest.mark.parametrize("metric,kind", [
    (DistanceType.L2Expanded, CodebookGen.PER_SUBSPACE),
    (DistanceType.InnerProduct, CodebookGen.PER_SUBSPACE),
    (DistanceType.L2SqrtExpanded, CodebookGen.PER_CLUSTER),
])
def test_bit_identity_across_metrics_and_codebooks(metric, kind):
    res = Resources(seed=0)
    # 900 rows over 37 lists: ragged sizes, ragged LAST list included
    db, idx = _build(metric=metric, codebook_kind=kind, res=res)
    sizes = np.asarray(idx.list_sizes)
    assert sizes.min() != sizes.max()  # genuinely ragged
    t = tiered.TieredIvfPq.from_index(idx, res=res)
    rng = np.random.default_rng(1)
    params = SearchParams(n_probes=9, scan_mode="cache")
    for nq in (3, 17):  # two query shapes -> two compiled buckets
        q = rng.standard_normal((nq, db.shape[1]), dtype=np.float32)
        _assert_identical(t, idx, q, 7, params, res)


def test_bit_identity_with_overflow_block():
    res = Resources(seed=0)
    rng = np.random.default_rng(2)
    # skewed mass -> rows spill past the capped list_pad
    db = np.concatenate([
        rng.standard_normal((600, 16), dtype=np.float32) * 0.05,
        rng.standard_normal((200, 16), dtype=np.float32) * 3.0,
    ]).astype(np.float32)
    idx = ivf_pq.build(db, IndexParams(n_lists=16, pq_dim=8,
                                       kmeans_n_iters=4), res=res)
    assert idx.overflow_codes.shape[0] > 0  # the shape under test
    t = tiered.TieredIvfPq.from_index(idx, res=res)
    q = rng.standard_normal((5, 16), dtype=np.float32)
    _assert_identical(t, idx, q, 9,
                      SearchParams(n_probes=6, scan_mode="cache"), res)


def test_bit_identity_through_eviction_churn():
    res = Resources(seed=0)
    db, idx = _build(n_lists=64, rows=1200, res=res)
    # 24 slots for 64 lists: every batch below evicts somebody
    arena = tiered.SlabArena(24, int(idx.list_codes.shape[1]),
                             idx.rot_dim)
    t = tiered.TieredIvfPq.from_index(idx, res=res, arena=arena)
    rng = np.random.default_rng(3)
    params = SearchParams(n_probes=3, scan_mode="cache")
    for _ in range(10):
        q = rng.standard_normal((4, db.shape[1]), dtype=np.float32) * 2.0
        _assert_identical(t, idx, q, 5, params, res)
    counts = arena.snapshot_counts()
    assert counts["evictions"] > 0  # churn actually happened
    assert counts["inserts"] - counts["occupancy"] == counts["evictions"]


def test_zero_compiles_on_steady_state_hit_path():
    res = Resources(seed=0)
    db, idx = _build(n_lists=16, rows=400, pq_dim=8, res=res)
    t = tiered.TieredIvfPq.from_index(idx, res=res)
    rng = np.random.default_rng(4)
    params = SearchParams(n_probes=16, scan_mode="cache")
    q = rng.standard_normal((4, db.shape[1]), dtype=np.float32)
    t.search(q, 5, params, res=res)  # warm: compiles + fills the arena
    before = serving.compile_count()
    for _ in range(3):
        q = rng.standard_normal((4, db.shape[1]), dtype=np.float32)
        t.search(q, 5, params, res=res)
    assert serving.compile_count() == before


def test_rejects_non_cache_scan_mode():
    res = Resources(seed=0)
    _, idx = _build(n_lists=8, rows=200, pq_dim=8, res=res)
    t = tiered.TieredIvfPq.from_index(idx, res=res)
    with pytest.raises(ValueError, match="scan_mode"):
        t.search(np.zeros((2, 24), np.float32), 3,
                 SearchParams(scan_mode="lut"), res=res)


# ------------------------------------------------------------ Batcher.peek


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def _req(k=10, t=0.0, deadline=None):
    return Request(np.zeros(8, np.float32), k, Future(), t,
                   t_deadline=deadline)


def test_peek_is_non_consuming_and_same_k_prefix():
    clock = FakeClock()
    b = Batcher(max_batch=4, max_wait_us=10_000_000, clock=clock)
    rs = [_req(k=10), _req(k=10), _req(k=5), _req(k=10)]
    for r in rs:
        b.put(r)
    view = b.peek()
    assert view == [rs[0], rs[1], rs[3]]  # head's k group, FIFO
    assert b.peek() == view               # idempotent
    assert len(b) == 4                    # nothing consumed
    with b.locked():
        batch = b.select(clock())
    assert batch is None or batch == view  # peek never changed selection


def test_peek_caps_at_max_batch():
    b = Batcher(max_batch=2, max_wait_us=10_000_000, clock=FakeClock())
    for _ in range(5):
        b.put(_req())
    assert len(b.peek()) == 2


def test_deadline_pruning_identical_with_and_without_peek():
    def run(peek_first):
        clock = FakeClock()
        b = Batcher(max_batch=8, max_wait_us=1000, clock=clock)
        live = _req(t=0.0)
        doomed = _req(t=0.0, deadline=0.5)
        b.put(live)
        b.put(doomed)
        clock.t = 1.0  # doomed's shed deadline passed, flush deadline too
        if peek_first:
            view = b.peek()
            # expired requests are filtered from the VIEW but stay
            # queued: peek must not consume the select path's pruning
            assert view == [live]
            assert len(b) == 2
        with b.locked():
            batch = b.select(clock.t)
        return batch, b.pop_expired()

    batch_a, expired_a = run(peek_first=True)
    batch_b, expired_b = run(peek_first=False)
    assert [r.k for r in batch_a] == [r.k for r in batch_b] == [10]
    assert len(expired_a) == len(expired_b) == 1


def test_peek_empty_and_all_expired_returns_none():
    clock = FakeClock()
    b = Batcher(max_batch=8, max_wait_us=1000, clock=clock)
    assert b.peek() is None
    b.put(_req(t=0.0, deadline=0.5))
    clock.t = 1.0
    assert b.peek() is None
    assert len(b) == 1  # still queued for select's pruning


# ------------------------------------------------------- solve_host_tier


def test_solve_host_tier_predictions_are_exact():
    res = Resources(seed=0)
    _, idx = _build(n_lists=32, rows=800, pq_dim=8, res=res)
    t = tiered.TieredIvfPq.from_index(idx, res=res)
    plan = solve_host_tier(
        t.tier.n_lists, t.tier.list_pad, idx.rot_dim,
        t.tier.n_code_bytes, res.workspace_limit_bytes)
    assert plan["arena_slots"] == t.arena.slots
    # the C001 acceptance bound is <= 1.5x; the model is in fact exact
    assert plan["arena_bytes"] == t.arena.nbytes
    assert plan["host_bytes"] == t.tier.nbytes
    assert plan["arena_slots"] * plan["slab_bytes"] == plan["arena_bytes"]
    assert 1 <= plan["arena_slots"] <= t.tier.n_lists
    assert plan["predicted_fetch_s"] > 0
    assert plan["worst_batch_distinct"] <= t.tier.n_lists


def test_arena_smaller_than_one_batch_is_a_typed_error():
    res = Resources(seed=0)
    _, idx = _build(n_lists=32, rows=800, pq_dim=8, res=res)
    arena = tiered.SlabArena(4, int(idx.list_codes.shape[1]), idx.rot_dim)
    t = tiered.TieredIvfPq.from_index(idx, res=res, arena=arena)
    with pytest.raises(tiered.TieredArenaError, match="slots"):
        t.search(np.zeros((8, 24), np.float32), 3,
                 SearchParams(n_probes=16, scan_mode="cache"), res=res)


# ------------------------------------------------------------- telemetry


def test_tier_metrics_reconcile_with_counters_and_docs():
    res = Resources(seed=0)
    _, idx = _build(n_lists=32, rows=800, pq_dim=8, res=res)
    reg = obs_metrics.Registry()
    arena = tiered.SlabArena(16, int(idx.list_codes.shape[1]),
                             idx.rot_dim, registry=reg, label="t")
    t = tiered.TieredIvfPq.from_index(idx, res=res, arena=arena)
    rng = np.random.default_rng(5)
    params = SearchParams(n_probes=4, scan_mode="cache")
    for _ in range(4):
        q = rng.standard_normal((3, 24), dtype=np.float32)
        t.search(q, 5, params, res=res)
    t.prefetch_queries(rng.standard_normal((3, 24), dtype=np.float32),
                       params=params)
    c = arena.snapshot_counts()

    def val(name, *labels):
        fam = reg.get(name)
        assert fam is not None, name
        return dict(fam.collect())[labels].value

    assert val("raft_tpu_tier_cache_hits_total", "t") == c["hits"]
    assert val("raft_tpu_tier_cache_misses_total", "t") == c["misses"]
    assert val("raft_tpu_tier_cache_evictions_total", "t") \
        == c["evictions"]
    assert val("raft_tpu_tier_prefetch_total", "t", "fetch") \
        == c["prefetch_fetches"]
    assert val("raft_tpu_tier_prefetch_total", "t", "already_resident") \
        == c["prefetch_hits"]
    assert val("raft_tpu_tier_prefetch_total", "t", "useful") \
        == c["useful_prefetch"]
    assert val("raft_tpu_tier_arena_occupancy", "t") \
        == c["occupancy"] / arena.slots
    # every stall observation is one histogram count; both paths labeled
    hist = dict(reg.get("raft_tpu_tier_fetch_stall_seconds").collect())
    assert ("t", "demand") in hist and ("t", "prefetch") in hist
    assert hist[("t", "demand")].count > 0

    with open(os.path.join(REPO, "docs", "observability.md")) as f:
        docs = f.read()
    for name in ("raft_tpu_tier_cache_hits_total",
                 "raft_tpu_tier_cache_misses_total",
                 "raft_tpu_tier_cache_evictions_total",
                 "raft_tpu_tier_prefetch_total",
                 "raft_tpu_tier_fetch_stall_seconds",
                 "raft_tpu_tier_arena_occupancy",
                 "tier_fetch"):
        assert name in docs, f"{name} missing from docs/observability.md"


def test_tier_fetch_spans_carry_requesting_trace():
    res = Resources(seed=0)
    _, idx = _build(n_lists=16, rows=400, pq_dim=8, res=res)
    sink = obs_spans.ListSink()
    arena = tiered.SlabArena(16, int(idx.list_codes.shape[1]),
                             idx.rot_dim, span_sink=sink)
    t = tiered.TieredIvfPq.from_index(idx, res=res, arena=arena)
    with obs_spans.trace_scope("trace-under-test"):
        t.search(np.zeros((2, 24), np.float32), 3,
                 SearchParams(n_probes=4, scan_mode="cache"), res=res)
    fetches = [s for s in sink.records if s["kind"] == "tier_fetch"]
    assert fetches, "the cold search must have fetched"
    for s in fetches:
        assert s["trace"] == "trace-under-test"
        assert s["path"] == "demand"
        assert s["namespace"] == t.namespace
        assert len(s["clusters"]) == len(s["slots"])
        assert s["stall_s"] >= 0
        json.dumps(s)  # JSONL-serializable like every span


def test_namespace_multiplexing_two_indexes_one_arena():
    res = Resources(seed=0)
    db_a, idx_a = _build(n_lists=16, rows=400, pq_dim=8, seed=10, res=res)
    db_b, idx_b = _build(n_lists=16, rows=400, pq_dim=8, seed=11, res=res)
    arena = tiered.SlabArena(20, int(idx_a.list_codes.shape[1]),
                             idx_a.rot_dim)
    ta = tiered.TieredIvfPq.from_index(idx_a, res=res, arena=arena,
                                       namespace="a")
    tb = tiered.TieredIvfPq.from_index(idx_b, res=res, arena=arena,
                                       namespace="b")
    rng = np.random.default_rng(6)
    params = SearchParams(n_probes=4, scan_mode="cache")
    # interleave the tenants: each stays bit-identical to its own
    # all-HBM reference even while the other churns shared slots
    for _ in range(4):
        qa = rng.standard_normal((2, 24), dtype=np.float32)
        qb = rng.standard_normal((2, 24), dtype=np.float32)
        _assert_identical(ta, idx_a, qa, 5, params, res)
        _assert_identical(tb, idx_b, qb, 5, params, res)
    with arena._lock:
        namespaces = {ns for ns, _ in arena._map}
    assert namespaces == {"a", "b"}


# ----------------------------------------------------------- degradation


def test_host_read_failure_is_typed_and_chained():
    res = Resources(seed=0)
    _, idx = _build(n_lists=16, rows=400, pq_dim=8, res=res)
    t = tiered.TieredIvfPq.from_index(idx, res=res)
    t.tier.norms = None  # simulate a torn/unmapped host buffer
    with pytest.raises(tiered.TierReadError) as ei:
        t.search(np.zeros((2, 24), np.float32), 3,
                 SearchParams(n_probes=4, scan_mode="cache"), res=res)
    assert ei.value.__cause__ is not None
    # arena state must be untouched: the read failed BEFORE any insert
    assert t.arena.occupancy() == 0


def test_host_read_failure_through_engine_is_batchfailed_not_hang():
    res = Resources(seed=0)
    _, idx = _build(n_lists=16, rows=400, pq_dim=8, res=res)
    t = tiered.TieredIvfPq.from_index(idx, res=res)
    searcher = serving.tiered_ivf_pq_searcher(
        t, SearchParams(n_probes=4, scan_mode="cache"), res=res)
    engine = serving.Engine(searcher, serving.EngineConfig(
        max_batch=4, max_wait_us=500, warm_ks=(3,)))
    engine.start()
    try:
        t.tier.norms = None  # break the tier AFTER warmup
        fut = engine.submit(np.ones(24, np.float32), 3)
        with pytest.raises(BatchFailed) as ei:
            fut.result(timeout=30)
        assert isinstance(ei.value.__cause__, tiered.TierReadError)
    finally:
        engine.stop()


# -------------------------------------------------------------- manifest


def test_manifest_roundtrip_and_artifact_checker(tmp_path):
    res = Resources(seed=0)
    db, idx = _build(n_lists=16, rows=400, pq_dim=8, res=res)
    t = tiered.TieredIvfPq.from_index(idx, res=res)
    rng = np.random.default_rng(7)
    q = rng.standard_normal((3, 24), dtype=np.float32)
    params = SearchParams(n_probes=4, scan_mode="cache")
    v0, i0 = t.search(q, 5, params, res=res)

    mp = tiered.save_tiered(t, str(tmp_path), name="test")
    t2 = tiered.load_tiered(mp, res=res)
    v1, i1 = t2.search(q, 5, params, res=res)
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
    np.testing.assert_array_equal(_bits(v0), _bits(v1))

    from raft_tpu.analysis.artifacts import _CHECKERS, artifact_kind
    name = os.path.basename(mp)
    assert artifact_kind(name) == "tiered_manifest"
    with open(mp) as f:
        art = json.load(f)
    _CHECKERS["tiered_manifest"](art, mp)  # committed-form validation

    codes_path = tmp_path / art["files"]["codes"]["path"]
    with open(codes_path, "r+b") as f:
        f.seek(32)
        f.write(b"\xff\xff")
    with pytest.raises(ValueError, match="crc32"):
        _CHECKERS["tiered_manifest"](art, mp)


def test_manifest_schema_rejections():
    with pytest.raises(ValueError):
        tiered.validate_manifest({"schema": "wrong/v0"})
    art = {"schema": tiered.MANIFEST_SCHEMA}
    with pytest.raises(ValueError):
        tiered.validate_manifest(art)  # geometry keys missing


# ----------------------------------------------------- thread discipline


def test_guarded_by_annotations_cover_tiered_shared_state():
    fields = guarded_fields(
        os.path.join(REPO, "raft_tpu", "neighbors", "tiered.py"))
    for name in ("_dec", "_norms", "_ids", "_sizes", "_map", "_free",
                 "_prefetched", "counts"):
        assert name in fields, name


def _race_once(seed, idx_a, idx_b, queries, res):
    """One amplified schedule: two tenants share one arena while a
    searcher thread, a prefetcher-path thread, and an eviction-heavy
    searcher run concurrently. Returns (counts, errors)."""
    arena = tiered.SlabArena(12, int(idx_a.list_codes.shape[1]),
                             idx_a.rot_dim, label=f"race{seed}")
    ta = tiered.TieredIvfPq.from_index(idx_a, res=res, arena=arena,
                                       namespace="a")
    tb = tiered.TieredIvfPq.from_index(idx_b, res=res, arena=arena,
                                       namespace="b")
    params = SearchParams(n_probes=2, scan_mode="cache")
    errors = []

    def searcher(t):
        def run():
            try:
                for q in queries:
                    t.search(q, 3, params, res=res)
            except Exception as e:  # pragma: no cover - the assertion
                errors.append(e)
        return run

    def prefetcher(t):
        def run():
            try:
                for q in queries:
                    t.prefetch_queries(q, params=params)
            except Exception as e:  # pragma: no cover
                errors.append(e)
        return run

    with InterleaveAmplifier(seed=seed, yield_probability=0.15,
                             path_filters=("raft_tpu",)):
        threads = [threading.Thread(target=f) for f in
                   (searcher(ta), searcher(tb), prefetcher(ta),
                    prefetcher(tb))]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
    return arena.snapshot_counts(), errors


def _assert_reconciles(c):
    assert (c["hits"] + c["misses"] + c["prefetch_hits"]
            + c["prefetch_fetches"] == c["resolved"]), c
    assert c["inserts"] == c["misses"] + c["prefetch_fetches"], c
    assert c["evictions"] == c["inserts"] - c["occupancy"], c


def test_eviction_race_reconciles_fast():
    res = Resources(seed=0)
    _, idx_a = _build(n_lists=24, rows=500, pq_dim=8, seed=20, res=res)
    _, idx_b = _build(n_lists=24, rows=500, pq_dim=8, seed=21, res=res)
    rng = np.random.default_rng(8)
    queries = [rng.standard_normal((2, 24), dtype=np.float32)
               for _ in range(4)]
    for seed in seeds(3):
        counts, errors = _race_once(seed, idx_a, idx_b, queries, res)
        assert not errors, errors
        _assert_reconciles(counts)
        assert counts["evictions"] > 0  # 12 slots, 48 namespaced lists


@pytest.mark.slow
@pytest.mark.interleave
def test_eviction_race_reconciles_100_amplified_seeds():
    res = Resources(seed=0)
    _, idx_a = _build(n_lists=24, rows=500, pq_dim=8, seed=20, res=res)
    _, idx_b = _build(n_lists=24, rows=500, pq_dim=8, seed=21, res=res)
    rng = np.random.default_rng(9)
    queries = [rng.standard_normal((2, 24), dtype=np.float32)
               for _ in range(3)]
    # warm every compiled shape OUTSIDE the amplifier: the sweep should
    # spend its schedules on the arena's locking, not on XLA compiles
    warm, _ = _race_once(0, idx_a, idx_b, queries, res)
    _assert_reconciles(warm)
    for seed in seeds(100):
        counts, errors = _race_once(seed, idx_a, idx_b, queries, res)
        assert not errors, (seed, errors)
        _assert_reconciles(counts)


# ------------------------------------------------------------- CPU smoke


def test_cpu_smoke_tier_under_deadline_policy():
    """The acceptance smoke: a synthetic index >= 4x the arena served
    through the engine + prefetcher under the deadline/shed policy —
    hit-rate < 1.0 (the tier is actually paging), nonzero useful
    prefetches (the peek loop actually helps), and every submitted
    request resolves to a typed outcome (zero untyped failures)."""
    from raft_tpu.serving.batcher import DeadlineExceeded, QueueFull

    res = Resources(seed=0)
    db, idx = _build(n_lists=64, rows=1600, pq_dim=8, seed=30, res=res)
    arena = tiered.SlabArena(16, int(idx.list_codes.shape[1]),
                             idx.rot_dim, label="smoke")
    assert idx.n_lists >= 4 * arena.slots
    t = tiered.TieredIvfPq.from_index(idx, res=res, arena=arena)
    params = SearchParams(n_probes=2, scan_mode="cache")
    searcher = serving.tiered_ivf_pq_searcher(t, params, res=res)
    # a LONG coalescing window (20 ms) so partial batches sit in the
    # queue where the 0.1 ms peek loop can stage them pre-dispatch —
    # that's the overlap the prefetcher exists to buy
    engine = serving.Engine(searcher, serving.EngineConfig(
        max_batch=8, max_wait_us=20_000, warm_ks=(3,),
        queue_limit=32, queue_high_watermark=8))
    engine.start()
    pf = tiered.attach_prefetcher(engine, t, params=params, poll_s=1e-4)
    rng = np.random.default_rng(10)
    outcomes = {"ok": 0, "shed": 0}
    try:
        import time as _time
        futs = []
        for _ in range(120):
            q = rng.standard_normal(24).astype(np.float32)
            try:
                futs.append(engine.submit(q, 3, block=False,
                                          deadline_ms=5000.0))
            except (serving.Overloaded, serving.CircuitOpen, QueueFull):
                outcomes["shed"] += 1
            _time.sleep(0.002)  # paced arrivals: batches actually form
        for f in futs:
            try:
                f.result(timeout=60)  # a hang here is the failure mode
                outcomes["ok"] += 1
            except DeadlineExceeded:
                outcomes["shed"] += 1
    finally:
        pf.close()
        engine.stop()
    assert outcomes["ok"] + outcomes["shed"] == 120  # all typed
    assert outcomes["ok"] > 0
    assert pf.n_errors == 0
    c = arena.snapshot_counts()
    _assert_reconciles(c)
    demand = c["hits"] + c["misses"]
    assert demand > 0
    assert c["hits"] / demand < 1.0          # the tier actually paged
    assert c["useful_prefetch"] > 0          # prefetch actually helped
    plan = solve_host_tier(t.tier.n_lists, t.tier.list_pad, idx.rot_dim,
                           t.tier.n_code_bytes,
                           res.workspace_limit_bytes)
    # C001 drift gate is [1/1.5, 1.5]; the byte model is exact
    assert plan["slab_bytes"] * arena.slots == arena.nbytes
    assert plan["host_bytes"] == t.tier.nbytes


def test_prefetcher_stages_peeked_batch_before_dispatch():
    """Direct peek-path check without racing the engine: stage a batch
    in a stopped batcher, run one prefetch pass by hand, and the demand
    resolve must then hit 100% with useful_prefetch counted."""
    res = Resources(seed=0)
    _, idx = _build(n_lists=16, rows=400, pq_dim=8, res=res)
    arena = tiered.SlabArena(16, int(idx.list_codes.shape[1]),
                             idx.rot_dim)
    t = tiered.TieredIvfPq.from_index(idx, res=res, arena=arena)
    params = SearchParams(n_probes=4, scan_mode="cache")
    rng = np.random.default_rng(11)
    q = rng.standard_normal((3, 24), dtype=np.float32)
    n = t.prefetch_queries(q, params=params)
    assert n > 0
    before = arena.snapshot_counts()
    t.search(q, 5, params, res=res)
    after = arena.snapshot_counts()
    assert after["misses"] == before["misses"]  # all demand hits
    assert after["useful_prefetch"] > before["useful_prefetch"]
