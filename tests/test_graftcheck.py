"""graftcheck suite: Tier-A rules on one-violation fixtures (plus clean
twins), the baseline round-trip, and the Tier-B jaxpr memory audit
cross-checked against the itemized LUT model from docs/tuning.md."""
import json
import os

import pytest
from graftcheck_util import (REPO, check_suppression, check_twin,
                             fixture_mod as _mod, fixture_src, inject,
                             run_cli, tmp_mod)

from raft_tpu.analysis import (AST_RULES, ModuleInfo, check_layering,
                               load_baseline, run_tier_a, save_baseline,
                               split_by_baseline)
from raft_tpu.analysis.rules_ast import (rule_host_sync, rule_recompile_hazard,
                                         rule_traced_branch,
                                         rule_unattributed_dispatch,
                                         rule_unguarded_broadcast,
                                         rule_untraced_entry_point)


# ------------------------------------------------------------ Tier A rules

@pytest.mark.parametrize("rule,rule_id,stem,expect_qual", [
    (rule_host_sync, "R001", "r001", "pulls_to_host"),
    (rule_traced_branch, "R002", "r002", "branches_on_tracer"),
    (rule_recompile_hazard, "R003", "r003", "compiles_every_iteration"),
    (rule_unguarded_broadcast, "R005", "r005", "gathers_everything"),
], ids=["R001", "R002", "R003", "R005"])
def test_rule_flags_bad_and_passes_clean(rule, rule_id, stem, expect_qual):
    check_twin(rule, rule_id, stem, expect_qual)


def test_clean_twins_pass_every_rule():
    for fname in ("r001_clean.py", "r002_clean.py", "r003_clean.py",
                  "r005_clean.py"):
        mod = _mod(fname, f"raft_tpu.fixture_pkg_b.{fname[:-3]}")
        for rule in AST_RULES:
            assert rule(mod) == [], (fname, rule.__name__)


def test_r006_flags_untraced_entry_points_in_neighbors_scope():
    # R006 is scoped to raft_tpu.neighbors submodules, so the fixtures
    # are analysed under that modname rather than fixture_pkg_b
    found = rule_untraced_entry_point(
        _mod("r006_bad.py", "raft_tpu.neighbors.r006_bad"))
    assert [(f.rule, f.qualname) for f in found] == [
        ("R006", "build"), ("R006", "search")]
    assert "tracing" in found[0].message
    assert rule_untraced_entry_point(
        _mod("r006_clean.py", "raft_tpu.neighbors.r006_clean")) == []


def test_r006_ignores_modules_outside_neighbors():
    # the same undecorated entry points are fine anywhere else
    for modname in ("raft_tpu.fixture_pkg_b.r006_bad",
                    "raft_tpu.neighbors",  # the package __init__ itself
                    "tools.r006_bad"):
        assert rule_untraced_entry_point(_mod("r006_bad.py", modname)) == []


def test_r006_suppression_on_def_line(tmp_path):
    src = fixture_src("r006_bad.py")
    src = src.replace("def build(dataset):",
                      "def build(dataset):  # graftcheck: R006")
    mod = tmp_mod(tmp_path, "r006_suppressed.py", src,
                  "raft_tpu.neighbors.r006_suppressed")
    assert [f.qualname for f in rule_untraced_entry_point(mod)] == ["search"]


def test_r006_repo_entry_points_are_all_traced():
    # the live neighbors package must satisfy R006 with zero baseline
    # entries — the instrumentation is the contract, not an exception
    import raft_tpu.neighbors as npkg
    pkg_dir = os.path.dirname(npkg.__file__)
    findings = []
    for fn in sorted(os.listdir(pkg_dir)):
        if not fn.endswith(".py"):
            continue
        mod = ModuleInfo(os.path.join(pkg_dir, fn),
                         f"raft_tpu/neighbors/{fn}",
                         f"raft_tpu.neighbors.{fn[:-3]}")
        findings.extend(rule_untraced_entry_point(mod))
    assert findings == [], [f.format() for f in findings]


def test_r007_flags_unattributed_dispatch_in_scope():
    # R007 is scoped to raft_tpu.neighbors/raft_tpu.ops modules
    found = rule_unattributed_dispatch(
        _mod("r007_bad.py", "raft_tpu.neighbors.r007_bad"))
    assert [(f.rule, f.qualname) for f in found] == [
        ("R007", "silently_falls_back")]
    assert "record_dispatch" in found[0].message
    assert rule_unattributed_dispatch(
        _mod("r007_clean.py", "raft_tpu.neighbors.r007_clean")) == []


def test_r007_ignores_out_of_scope_and_exempt_modules():
    # the same silent fallback is fine outside neighbors/ops, and the
    # module defining the dispatch helpers is not a dispatch site
    for modname in ("raft_tpu.fixture_pkg_b.r007_bad",
                    "raft_tpu.ops.pallas_kernels",
                    "tools.r007_bad"):
        assert rule_unattributed_dispatch(
            _mod("r007_bad.py", modname)) == []


def test_r007_suppression_on_dispatch_line(tmp_path):
    check_suppression(rule_unattributed_dispatch, tmp_path, "r007_bad.py",
                      'pk.fused_dispatch("brute_force", scan_mode)', "R007",
                      modname="raft_tpu.neighbors.r007_supp")


def test_r007_repo_dispatch_sites_are_all_attributed():
    # the live neighbors/ops packages must satisfy R007 with zero
    # baseline entries — and the rule must actually SEE the dispatch
    # sites (a resolver regression would pass vacuously otherwise)
    import ast as _ast

    import raft_tpu.neighbors as npkg
    import raft_tpu.ops as opkg
    import raft_tpu.parallel as ppkg
    import raft_tpu.planner as plpkg
    from raft_tpu.analysis.rules_ast import DISPATCH_CALLS
    findings, seen_dispatch = [], 0
    seen_by_prefix = {}
    for pkg, prefix in ((npkg, "raft_tpu.neighbors"),
                        (opkg, "raft_tpu.ops"),
                        (ppkg, "raft_tpu.parallel"),
                        (plpkg, "raft_tpu.planner")):
        pkg_dir = os.path.dirname(pkg.__file__)
        for fn in sorted(os.listdir(pkg_dir)):
            if not fn.endswith(".py"):
                continue
            mod = ModuleInfo(os.path.join(pkg_dir, fn),
                             f"{prefix.replace('.', '/')}/{fn}",
                             f"{prefix}.{fn[:-3]}")
            findings.extend(rule_unattributed_dispatch(mod))
            if mod.modname not in (f"{prefix}.pallas_kernels",):
                n = 0
                for node in _ast.walk(mod.tree):
                    if not isinstance(node, _ast.Call):
                        continue
                    dotted = mod.resolve(node.func)
                    if dotted and "." not in dotted:
                        dotted = f"{mod.modname}.{dotted}"
                    n += dotted in DISPATCH_CALLS
                seen_dispatch += n
                seen_by_prefix[prefix] = seen_by_prefix.get(prefix, 0) + n
    assert findings == [], [f.format() for f in findings]
    assert seen_dispatch >= 4  # brute_force + ivf_flat + ivf_pq + cagra
    # the sharded search entry points (knn / cagra / ivf_pq / ivf_flat)
    # each plan their merge schedule through plan_sharded_search
    assert seen_by_prefix.get("raft_tpu.parallel", 0) >= 3
    # AdaptivePlanner.choose resolves the speed/recall operating point
    # through choose_operating_point (attributed via record_choice)
    assert seen_by_prefix.get("raft_tpu.planner", 0) >= 1


def test_layering_flags_cross_package_private_import():
    provider = _mod("r004_provider.py", "raft_tpu.fixture_pkg_a.r004_provider")
    bad = _mod("r004_bad.py", "raft_tpu.fixture_pkg_b.r004_bad")
    clean = _mod("r004_clean.py", "raft_tpu.fixture_pkg_b.r004_clean")
    found = check_layering([provider, bad, clean])
    assert [(f.rule, f.file, f.qualname) for f in found] == [
        ("R004", "tests/data/graftcheck/r004_bad.py", "<module>")]
    assert "_detail_kernel" in found[0].message


def test_layering_allows_same_package_private_use():
    provider = _mod("r004_provider.py", "raft_tpu.fixture_pkg_a.r004_provider")
    # same file re-declared as a sibling of the provider's package
    sibling = _mod("r004_bad.py", "raft_tpu.fixture_pkg_a.r004_bad")
    assert check_layering([provider, sibling]) == []


def test_inline_suppression(tmp_path):
    check_suppression(rule_traced_branch, tmp_path, "r002_bad.py",
                      "    if s:", "R002")


# ------------------------------------------------------- baseline handling

def test_baseline_round_trip(tmp_path):
    mod = _mod("r001_bad.py", "raft_tpu.fixture_pkg_b.r001_bad")
    findings = rule_host_sync(mod)
    path = tmp_path / "baseline.json"
    save_baseline(str(path), findings, {})
    baseline = load_baseline(str(path))
    new, suppressed = split_by_baseline(findings, baseline)
    assert new == [] and len(suppressed) == 1
    # keys survive line churn: same (rule, file, qualname), any line
    moved = [type(f)(f.rule, f.file, f.qualname, f.line + 40, f.message)
             for f in findings]
    new, suppressed = split_by_baseline(moved, baseline)
    assert new == [] and len(suppressed) == 1


def test_baseline_update_carries_justifications(tmp_path):
    mod = _mod("r001_bad.py", "raft_tpu.fixture_pkg_b.r001_bad")
    findings = rule_host_sync(mod)
    path = tmp_path / "baseline.json"
    save_baseline(str(path), findings, {})
    data = json.load(open(path))
    data["entries"][0]["justification"] = "measured, deliberate"
    json.dump(data, open(path, "w"))
    save_baseline(str(path), findings, load_baseline(str(path)))
    assert (json.load(open(path))["entries"][0]["justification"]
            == "measured, deliberate")


# --------------------------------------------------------------- the gate

def test_repo_is_clean_under_committed_baseline():
    findings = run_tier_a(REPO)
    baseline = load_baseline(os.path.join(REPO, "graftcheck_baseline.json"))
    new, _ = split_by_baseline(findings, baseline)
    assert new == [], "\n".join(f.format() for f in new)


def test_cli_nonzero_on_injected_violation(tmp_path):
    root = inject(tmp_path, "r001_bad.py", subdir="raft_tpu/fixture_pkg_b")
    proc = run_cli("--root", root, "--no-baseline")
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "R001" in proc.stdout and "pulls_to_host" in proc.stdout


# ------------------------------------------------------------------ Tier B

def test_jaxpr_walker_within_2x_of_itemized_lut_model():
    from raft_tpu.analysis import jaxpr_audit as ja
    budget = ja.DEFAULT_BUDGET_BYTES
    peak = ja.peak_live_bytes(ja.make_ivf_pq_lut_jaxpr(budget))
    oracle = ja.lut_itemized_peak(budget_bytes=budget)
    ratio = max(peak, oracle) / min(peak, oracle)
    assert ratio <= 2.0, (peak, oracle, ratio)


def test_audit_certifies_lut_search_at_sift1m_crash_shape():
    from raft_tpu.analysis import jaxpr_audit as ja
    budget = ja.DEFAULT_BUDGET_BYTES
    peak = ja.peak_live_bytes(ja.make_ivf_pq_lut_jaxpr(budget))
    assert peak <= budget


def test_audit_detects_pre_tiling_unbounded_variant():
    from raft_tpu.analysis import jaxpr_audit as ja
    budget = ja.DEFAULT_BUDGET_BYTES
    peak = ja.peak_live_bytes(
        ja.make_ivf_pq_lut_jaxpr(budget, unbounded_variant=True))
    assert peak > 4 * budget  # the sift-1M crash: ~5x over a 2 GiB budget


def test_audit_default_entries_all_within_budget():
    from raft_tpu.analysis import jaxpr_audit as ja
    results, findings = ja.run_audit()
    assert len(results) == 12
    assert findings == [], [f.format() for f in findings]
    assert all(r.ok for r in results)


# ------------------------------------------- justification placeholder gate

def test_unjustified_keys_flags_placeholder_and_empty():
    from raft_tpu.analysis import PLACEHOLDER_JUSTIFICATION, unjustified_keys

    baseline = {
        ("R001", "a.py", "f"): PLACEHOLDER_JUSTIFICATION,
        ("R002", "b.py", "g"): "",
        ("R003", "c.py", "h"): "   ",
        ("R004", "d.py", "i"): "measured on v5p, deliberate",
    }
    assert unjustified_keys(baseline) == [
        ("R001", "a.py", "f"), ("R002", "b.py", "g"),
        ("R003", "c.py", "h")]


def test_cli_fails_on_placeholder_justification(tmp_path):
    """A suppression without a reason is not a suppression: a baseline
    entry still carrying save_baseline's placeholder text must fail the
    run even when the findings themselves are all baselined."""
    from raft_tpu.analysis import PLACEHOLDER_JUSTIFICATION

    root = inject(tmp_path, "r001_bad.py", subdir="raft_tpu/fixture_pkg_b")
    baseline = tmp_path / "baseline.json"

    def run(*extra):
        return run_cli("--root", root, "--baseline", str(baseline), *extra)

    # record the baseline: save_baseline stamps the placeholder text
    proc = run("--update-baseline")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    entries = json.load(open(baseline))["entries"]
    assert all(e["justification"] == PLACEHOLDER_JUSTIFICATION
               for e in entries)

    # the very next gated run fails on the unjustified entries
    proc = run()
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "no real justification" in proc.stdout
    assert "not a suppression" in proc.stdout

    # writing a real justification clears the gate
    doc = json.load(open(baseline))
    for e in doc["entries"]:
        e["justification"] = "fixture: exercises the placeholder gate"
    json.dump(doc, open(baseline, "w"))
    proc = run()
    assert proc.returncode == 0, proc.stdout + proc.stderr
