"""graftcheck --threads suite: T001–T004 on one-violation fixture twins,
the derived thread model, the lock-order DOT export, and the repo gate
(every live finding fixed or baseline-justified)."""
import os

import pytest
from graftcheck_util import (REPO, check_suppression, check_twin,
                             fixture_mod as _mod, fixture_src, inject,
                             run_cli, tmp_mod as _util_tmp_mod)

from raft_tpu.analysis import load_baseline, split_by_baseline
from raft_tpu.analysis.concurrency import (THREAD_RULES, build_class_models,
                                           lock_order_dot,
                                           rule_blocking_while_locked,
                                           rule_condition_wait_loop,
                                           rule_lock_order,
                                           rule_unguarded_shared_state,
                                           run_threads)


def _tmp_mod(tmp_path, name, src):
    return _util_tmp_mod(tmp_path, name, src)


# ------------------------------------------------------------ T-rule twins

@pytest.mark.parametrize("rule,rule_id,stem,expect_qual", [
    (rule_unguarded_shared_state, "T001", "t001", "SharedCounter.count"),
    (rule_lock_order, "T002", "t002",
     "cycle:Transfer._credit_lock->Transfer._debit_lock"),
    (rule_blocking_while_locked, "T003", "t003", "Collector.run"),
    (rule_condition_wait_loop, "T004", "t004", "Gate.await_ready"),
], ids=["T001", "T002", "T003", "T004"])
def test_rule_flags_bad_and_passes_clean(rule, rule_id, stem, expect_qual):
    check_twin(rule, rule_id, stem, expect_qual)


def test_clean_twins_pass_every_thread_rule():
    for fname in ("t001_clean.py", "t002_clean.py", "t003_clean.py",
                  "t004_clean.py"):
        mod = _mod(fname)
        for rule in THREAD_RULES:
            assert rule(mod) == [], (fname, rule.__name__)


def test_t001_suppression_on_write_line(tmp_path):
    check_suppression(rule_unguarded_shared_state, tmp_path, "t001_bad.py",
                      "self.count = v + 1", "T001")


def test_t001_bogus_guard_name_is_its_own_finding(tmp_path):
    src = fixture_src("t001_bad.py")
    src = src.replace("self.count = 0",
                      "self.count = 0  # guarded_by: _no_such_lock")
    mod = _tmp_mod(tmp_path, "t001_bogus.py", src)
    found = rule_unguarded_shared_state(mod)
    assert [f.qualname for f in found] == ["SharedCounter.count"]
    assert "no such attribute" in found[0].message


def test_t001_atomic_escape_hatch(tmp_path):
    src = fixture_src("t001_bad.py")
    src = src.replace("self.count = 0",
                      "self.count = 0  # guarded_by: atomic")
    mod = _tmp_mod(tmp_path, "t001_atomic.py", src)
    assert rule_unguarded_shared_state(mod) == []


def test_t001_guarded_by_decorator_covers_method_writes(tmp_path):
    src = (
        "import threading\n"
        "from raft_tpu.analysis.concurrency import guarded_by\n\n\n"
        "class Box:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.value = 0\n\n"
        "    @guarded_by(\"_lock\")\n"
        "    def _set_locked(self, v):\n"
        "        self.value = v\n\n"
        "    def set(self, v):\n"
        "        with self._lock:\n"
        "            self._set_locked(v)\n"
    )
    mod = _tmp_mod(tmp_path, "t001_decorated.py", src)
    assert rule_unguarded_shared_state(mod) == []


def test_guarded_by_runtime_decorator_is_a_noop():
    from raft_tpu.analysis.concurrency import guarded_by

    @guarded_by("_lock")
    def f(x):
        return x + 1

    assert f(1) == 2


# --------------------------------------------------- derived thread model

def test_thread_targets_derived_from_spawn_sites():
    models = build_class_models(_mod("t001_bad.py"))
    (model,) = models
    assert model.roots["add"] == "thread"  # Thread(target=self.add)
    # public methods are client pseudo-roots, always multi-instance
    assert model.roots["spin"] == "client"
    assert "spin" in model.multi_roots


def test_spawn_under_loop_marks_root_multi_instance(tmp_path):
    src = (
        "import threading\n\n\n"
        "class Pool:\n"
        "    def __init__(self, n):\n"
        "        self._lock = threading.Lock()\n"
        "        self.n = n\n\n"
        "    def _work(self):\n"
        "        pass\n\n"
        "    def start(self):\n"
        "        for _ in range(self.n):\n"
        "            threading.Thread(target=self._work).start()\n"
    )
    mod = _tmp_mod(tmp_path, "pool.py", src)
    (model,) = build_class_models(mod)
    assert model.roots["_work"] == "thread"
    assert "_work" in model.multi_roots


def test_http_handler_do_methods_are_roots(tmp_path):
    src = (
        "from http.server import BaseHTTPRequestHandler\n\n\n"
        "class H(BaseHTTPRequestHandler):\n"
        "    def do_GET(self):\n"
        "        pass\n"
    )
    mod = _tmp_mod(tmp_path, "handler.py", src)
    (model,) = build_class_models(mod)
    assert model.roots["do_GET"] == "http"
    assert "do_GET" in model.multi_roots


def test_condition_canonicalizes_to_underlying_lock():
    (model,) = build_class_models(_mod("t004_bad.py"))
    assert model.canon_lock("_cv") == "_lock"


# ---------------------------------------------------------- lock-order DOT

def test_lock_order_dot_renders_cycle_red(tmp_path):
    root = inject(tmp_path, "t002_bad.py", as_name="transfer.py")
    dot = lock_order_dot(root)
    assert dot.startswith("digraph lock_order")
    assert '"Transfer._debit_lock" -> "Transfer._credit_lock"' in dot
    assert '"Transfer._credit_lock" -> "Transfer._debit_lock"' in dot
    assert "color=red" in dot


def test_repo_lock_order_graph_is_edge_free():
    """The serving/comms/obs stack follows a leaf-lock discipline: no
    code path holds two analyzer-visible locks at once, so the graph is
    all nodes, no edges — the authoritative lock-order statement that
    docs/serving.md and docs/robustness.md point at."""
    dot = lock_order_dot(REPO)
    assert "->" not in dot
    assert '"Engine._swap_lock"' in dot  # nodes still documented


# --------------------------------------------------------------- the gate

def test_repo_is_clean_under_committed_baseline():
    findings = run_threads(REPO)
    baseline = load_baseline(os.path.join(REPO, "graftcheck_baseline.json"))
    new, _ = split_by_baseline(findings, baseline)
    assert new == [], "\n".join(f.format() for f in new)


def test_cli_threads_nonzero_on_injected_violation(tmp_path):
    root = inject(tmp_path, "t001_bad.py")
    dot_path = tmp_path / "lock_order.dot"
    proc = run_cli("--root", root, "--no-baseline", "--threads",
                   "--dot", str(dot_path))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "T001" in proc.stdout
    assert "SharedCounter.count" in proc.stdout
    # the derived thread model is reported alongside the findings
    assert "[threads]" in proc.stdout
    assert dot_path.read_text().startswith("digraph lock_order")


def test_cli_dot_requires_threads(tmp_path):
    proc = run_cli("--root", str(tmp_path), "--dot", "-")
    assert proc.returncode == 2
    assert "--dot requires --threads" in proc.stderr


def test_cli_without_threads_skips_t_rules(tmp_path):
    root = inject(tmp_path, "t001_bad.py")
    proc = run_cli("--root", root, "--no-baseline")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "T001" not in proc.stdout
