"""uint8/int8 ivf_flat end-to-end — the reference's narrow-dtype indexes
(ivf_flat int8/uint8 with dp4a scans, ivf_flat_interleaved_scan-inl.cuh:
99-251). On TPU the win is bandwidth: int8 list storage reads 4x fewer
HBM bytes per probe than fp32; the scan upcasts in-register."""

import numpy as np
import pytest

from raft_tpu import Resources
from raft_tpu.neighbors import brute_force, ivf_flat
from raft_tpu.stats import neighborhood_recall

pytestmark = pytest.mark.fast


@pytest.mark.parametrize("dtype", [np.uint8, np.int8])
def test_narrow_dtype_matches_fp32(dtype):
    rng = np.random.default_rng(0)
    db_u = rng.integers(0, 256, (8000, 32)).astype(np.uint8)
    q_u = np.clip(db_u[rng.integers(0, 8000, 200)].astype(np.int32)
                  + rng.integers(-5, 6, (200, 32)), 0, 255).astype(np.uint8)
    if dtype == np.int8:
        db = (db_u.astype(np.int32) - 128).astype(np.int8)
        q = (q_u.astype(np.int32) - 128).astype(np.int8)
    else:
        db, q = db_u, q_u

    # fp32 control built from the SAME values (shifting preserves L2)
    dbf = db.astype(np.float32)
    qf = q.astype(np.float32)
    idx_f = ivf_flat.build(dbf, ivf_flat.IndexParams(n_lists=32),
                           res=Resources(seed=0))
    d_f, i_f = ivf_flat.search(idx_f, qf, 10,
                               ivf_flat.SearchParams(n_probes=8))

    idx_n = ivf_flat.build(db, ivf_flat.IndexParams(n_lists=32),
                           res=Resources(seed=0))
    assert idx_n.list_data.dtype == np.dtype(dtype)  # stored narrow
    d_n, i_n = ivf_flat.search(idx_n, q, 10,
                               ivf_flat.SearchParams(n_probes=8))

    # same clustering seed + exact int values → identical results
    np.testing.assert_array_equal(np.asarray(i_n), np.asarray(i_f))
    np.testing.assert_allclose(np.asarray(d_n), np.asarray(d_f), rtol=1e-5)

    # and the narrow path is a working index in its own right
    _, gt = brute_force.knn(qf, dbf, k=10, metric="sqeuclidean")
    rec = float(neighborhood_recall(np.asarray(i_n), np.asarray(gt)))
    assert rec >= 0.5  # probe-miss-bound on unclustered data, not dtype


def test_uint8_ivf_pq_and_cagra():
    """The other index families accept narrow dtypes too (reference:
    int8/uint8 ivf_pq and cagra instantiations, cpp/src/neighbors/)."""
    from raft_tpu.neighbors import cagra, ivf_pq

    rng = np.random.default_rng(0)
    db = rng.integers(0, 256, (8000, 32)).astype(np.uint8)
    q = db[rng.integers(0, 8000, 200)]
    _, gt = brute_force.knn(q.astype(np.float32), db.astype(np.float32),
                            k=10, metric="sqeuclidean")
    gt = np.asarray(gt)

    idx = ivf_pq.build(db, ivf_pq.IndexParams(n_lists=32, pq_dim=16),
                       res=Resources(seed=0))
    _, i_pq = ivf_pq.search(idx, q, 10, ivf_pq.SearchParams(n_probes=8))
    assert float(neighborhood_recall(np.asarray(i_pq), gt)) >= 0.6

    cg = cagra.build(db, cagra.IndexParams(graph_degree=16,
                                           intermediate_graph_degree=32),
                     res=Resources(seed=0))
    _, i_cg = cagra.search(cg, q, 10, cagra.SearchParams(itopk_size=32))
    assert float(neighborhood_recall(np.asarray(i_cg), gt)) >= 0.9
