"""select_k tests — compared against a numpy reference across shapes/algos
(reference pattern: cpp/test/matrix/select_k.cu)."""

import numpy as np
import pytest

from raft_tpu.ops import SelectAlgo, select_k


def _ref_select(values, k, select_min):
    order = np.argsort(values if select_min else -values, axis=-1, kind="stable")
    idx = order[..., :k]
    return np.take_along_axis(values, idx, -1), idx


@pytest.mark.parametrize(
    "algo", [SelectAlgo.DIRECT, SelectAlgo.TWO_PHASE, SelectAlgo.SCREEN,
             SelectAlgo.AUTO])
@pytest.mark.parametrize(
    "shape,k",
    [((4, 100), 10), ((1, 17), 17), ((7, 2048), 256), ((3, 100000), 64)])
@pytest.mark.parametrize("select_min", [True, False])
def test_select_k(algo, shape, k, select_min, rng):
    if shape[1] < 100 and algo == SelectAlgo.TWO_PHASE:
        pytest.skip("two-phase needs wide rows")
    values = rng.standard_normal(shape).astype(np.float32)
    got_v, got_i = select_k(values, k, select_min=select_min, algo=algo)
    want_v, _ = _ref_select(values, k, select_min)
    np.testing.assert_allclose(np.sort(np.asarray(got_v), -1),
                               np.sort(want_v, -1), rtol=1e-6)
    # indices must gather the returned values
    np.testing.assert_allclose(
        np.take_along_axis(values, np.asarray(got_i), -1), np.asarray(got_v), rtol=1e-6
    )


def test_select_k_with_source_indices(rng):
    values = rng.standard_normal((3, 50)).astype(np.float32)
    src = rng.integers(0, 10_000, size=(3, 50))
    got_v, got_i = select_k(values, 5, indices=src)
    want_v, want_pos = _ref_select(values, 5, True)
    np.testing.assert_allclose(np.sort(np.asarray(got_v)), np.sort(want_v), rtol=1e-6)
    assert set(np.asarray(got_i)[0]) == set(src[0][want_pos[0]])


def test_select_k_1d(rng):
    values = rng.standard_normal(100).astype(np.float32)
    v, i = select_k(values, 3)
    assert v.shape == (3,)
    np.testing.assert_allclose(np.asarray(v), np.sort(values)[:3], rtol=1e-6)


def test_k_too_large():
    with pytest.raises(ValueError):
        select_k(np.zeros((2, 4), np.float32), 5)


def test_two_phase_wide_rows(rng):
    """SELECT_LARGE_TEST analog: wide rows force the two-phase path under
    AUTO and must agree with numpy."""
    from raft_tpu.ops.select_k import SelectAlgo, select_k

    x = rng.standard_normal((4, 1 << 17)).astype(np.float32)
    for algo in (SelectAlgo.AUTO, SelectAlgo.TWO_PHASE):
        v, i = select_k(x, 32, select_min=True, algo=algo)
        ref = np.sort(x, axis=1)[:, :32]
        np.testing.assert_allclose(np.sort(np.asarray(v), 1), ref, rtol=1e-6)
        np.testing.assert_allclose(
            np.take_along_axis(x, np.asarray(i), 1), np.asarray(v), rtol=1e-6)


def test_two_phase_matches_direct_largest(rng):
    from raft_tpu.ops.select_k import SelectAlgo, select_k

    x = rng.standard_normal((3, 70_000)).astype(np.float32)
    v1, _ = select_k(x, 7, select_min=False, algo=SelectAlgo.DIRECT)
    v2, _ = select_k(x, 7, select_min=False, algo=SelectAlgo.TWO_PHASE)
    np.testing.assert_allclose(np.sort(np.asarray(v1), 1),
                               np.sort(np.asarray(v2), 1), rtol=1e-6)


@pytest.mark.parametrize("shape,k", [((16, 1000), 5), ((64, 4096), 32),
                                     ((8, 300), 10)])
def test_pallas_algo_matches_direct(shape, k, rng):
    """Streaming Pallas k-extraction agrees with lax.top_k (values exactly;
    indices up to ties)."""
    x = rng.standard_normal(shape).astype(np.float32)
    for select_min in (True, False):
        v_p, i_p = select_k(x, k, select_min=select_min,
                            algo=SelectAlgo.PALLAS)
        v_d, _ = select_k(x, k, select_min=select_min,
                          algo=SelectAlgo.DIRECT)
        np.testing.assert_allclose(np.asarray(v_p), np.asarray(v_d),
                                   rtol=1e-6)
        picked = np.take_along_axis(x, np.asarray(i_p), axis=1)
        np.testing.assert_allclose(picked, np.asarray(v_d), rtol=1e-6)


def test_pallas_inf_rows_and_wide_k(rng):
    """Rows with fewer than k finite entries emit -1 null indices (no
    duplicate picks); k wider than the column tile still selects exactly."""
    from raft_tpu.ops.pallas_kernels import pallas_select_k

    x = np.full((8, 256), np.inf, np.float32)
    x[:, 0] = 1.0
    x[:, 100] = 2.0
    v, i = pallas_select_k(x, 4, interpret=True)
    np.testing.assert_array_equal(np.asarray(i)[0], [0, 100, -1, -1])

    y = rng.standard_normal((8, 1024)).astype(np.float32)
    v, i = pallas_select_k(y, 200, tn=128, interpret=True)
    np.testing.assert_allclose(np.asarray(v), np.sort(y, 1)[:, :200],
                               rtol=1e-6)
    with pytest.raises(ValueError, match="small-k"):
        pallas_select_k(y, 1025, interpret=True)


def test_auto_uses_measured_table():
    """AUTO resolves DIRECT/TWO_PHASE from the per-platform measured
    crossover table (VERDICT r2 #6), overridable via set_auto_table."""
    import importlib

    # the ops package rebinds the name `select_k` to the function, so the
    # module must come from importlib
    sk = importlib.import_module("raft_tpu.ops.select_k")

    # cpu's measured table: DIRECT everywhere
    assert sk._resolve_auto(262144, 128) == sk.SelectAlgo.DIRECT
    # install a fake measured table and check band resolution
    sk.set_auto_table("cpu", {"32": 1024, "256": 4096, "inf": 16384})
    try:
        assert sk._resolve_auto(2048, 10) == sk.SelectAlgo.TWO_PHASE
        assert sk._resolve_auto(512, 10) == sk.SelectAlgo.DIRECT
        assert sk._resolve_auto(8192, 128) == sk.SelectAlgo.TWO_PHASE
        assert sk._resolve_auto(2048, 128) == sk.SelectAlgo.DIRECT
        assert sk._resolve_auto(32768, 1024) == sk.SelectAlgo.TWO_PHASE
        # k*4 > n guard: tiny rows always DIRECT
        assert sk._resolve_auto(2048, 1024) == sk.SelectAlgo.DIRECT
        # correctness is algo-independent: same results both ways
        rng = np.random.default_rng(0)
        x = rng.standard_normal((8, 8192)).astype(np.float32)
        vd, idd = select_k(x, 128, algo=SelectAlgo.DIRECT)
        vt, idt = select_k(x, 128, algo=SelectAlgo.TWO_PHASE)
        np.testing.assert_allclose(np.asarray(vd), np.asarray(vt))
        np.testing.assert_array_equal(np.asarray(idd), np.asarray(idt))
    finally:
        sk.set_auto_table("cpu", {"inf": sk._NEVER})


def test_screen_exact_values_and_indices(rng):
    """SCREEN is exact (values identical to a full sort) regardless of the
    approx threshold's recall — the τ certificate only needs k distinct
    elements (select_k.py _screen; reference bar: select_radix.cuh:54-67)."""
    for (b, n, k) in [(7, 500, 10), (4, 4096, 64), (3, 32768, 256)]:
        x = rng.standard_normal((b, n)).astype(np.float32)
        v, i = select_k(x, k, algo=SelectAlgo.SCREEN)
        v, i = np.asarray(v), np.asarray(i)
        np.testing.assert_array_equal(v, np.sort(x, axis=1)[:, :k])
        np.testing.assert_array_equal(np.take_along_axis(x, i, 1), v)
        assert all(len(set(r)) == k for r in i)


def test_screen_ties_and_inf_padding(rng):
    # heavy ties overflow the candidate buffer -> certified lax.cond
    # fallback to DIRECT; result must still be exact. 128 copies of 16
    # distinct values, k=20: count(x <= tau) >= 128 > m_buf = 104, so
    # the extract path CANNOT run — this pins the fallback branch.
    x = np.repeat(rng.standard_normal((3, 16)).astype(np.float32), 128,
                  axis=1)
    v, _ = select_k(x, 20, algo=SelectAlgo.SCREEN)
    np.testing.assert_array_equal(np.asarray(v), np.sort(x, 1)[:, :20])

    # IVF pad convention: +inf tails, including an all-inf row
    x = rng.standard_normal((4, 8192)).astype(np.float32)
    x[:, 4000:] = np.inf
    x[1, :] = np.inf
    v, _ = select_k(x, 64, algo=SelectAlgo.SCREEN)
    np.testing.assert_array_equal(np.asarray(v), np.sort(x, 1)[:, :64])
    assert np.all(np.asarray(v)[1] == np.inf)


def test_screen_filter_sparse_rows_and_neg_inf(rng):
    """Rows where most candidates are +inf (heavy bitset filters) but ≥ k
    survive get a finite certified τ via the FMAX clamp — and -inf values
    (legal smallest in min-mode) must never be clamped away."""
    x = rng.standard_normal((8, 16384)).astype(np.float32)
    drop = rng.random((8, 16384)) < 0.95  # 95% filtered away
    x = np.where(drop, np.inf, x).astype(np.float32)
    v, i = select_k(x, 10, algo=SelectAlgo.SCREEN)
    np.testing.assert_array_equal(np.asarray(v), np.sort(x, 1)[:, :10])
    np.testing.assert_array_equal(
        np.take_along_axis(x, np.asarray(i), 1), np.asarray(v))

    y = rng.standard_normal((4, 4096)).astype(np.float32)
    y[0, 7] = -np.inf
    y[2, 100:110] = -np.inf
    v, i = select_k(y, 16, algo=SelectAlgo.SCREEN)
    np.testing.assert_array_equal(np.asarray(v), np.sort(y, 1)[:, :16])
    assert np.asarray(v)[0, 0] == -np.inf and np.asarray(i)[0, 0] == 7


def test_screen_int_dtype_falls_back(rng):
    xi = rng.integers(0, 1000, (3, 256)).astype(np.int32)
    v, _ = select_k(xi, 5, algo=SelectAlgo.SCREEN)
    np.testing.assert_array_equal(np.asarray(v), np.sort(xi, 1)[:, :5])


def test_auto_nested_screen_table():
    """AUTO consumes the nested {two_phase, screen} crossover form the
    r4 select_k_bench artifacts emit; SCREEN outranks TWO_PHASE where
    both bands cover, and int dtypes never take SCREEN."""
    import importlib

    sk = importlib.import_module("raft_tpu.ops.select_k")
    sk.set_auto_table("cpu", {"two_phase": {"inf": 65536},
                              "screen": {"64": 8192, "inf": 32768}})
    try:
        assert sk._resolve_auto(16384, 10) == sk.SelectAlgo.SCREEN
        assert sk._resolve_auto(4096, 10) == sk.SelectAlgo.DIRECT
        assert sk._resolve_auto(16384, 128) == sk.SelectAlgo.DIRECT
        assert sk._resolve_auto(40000, 128) == sk.SelectAlgo.SCREEN
        assert sk._resolve_auto(100000, 128) == sk.SelectAlgo.SCREEN
        # int rows can't ride approx/inf-padding
        assert sk._resolve_auto(16384, 10,
                                floating=False) == sk.SelectAlgo.DIRECT
        # screen-only nested table: two_phase never fires
        sk.set_auto_table("cpu", {"screen": {"inf": 8192}})
        assert sk._resolve_auto(16384, 10) == sk.SelectAlgo.SCREEN
        assert sk._resolve_auto(4096, 10) == sk.SelectAlgo.DIRECT
    finally:
        sk.set_auto_table("cpu", {"inf": sk._NEVER})


def test_topk_pad_rules():
    """Measured k-pad rules rewrite DIRECT's requested k at trace time
    (exact: the prefix of a larger selection IS the smaller selection,
    ties included); rules match exact k within a x1.25 width window."""
    import importlib

    import jax

    sk = importlib.import_module("raft_tpu.ops.select_k")
    plat = jax.default_backend()
    # save/restore the platform's prior rules (may include the shipped
    # builtin on a tpu/axon run) — set_pad_rules(plat, None) pops the
    # whole entry, which would leave later tests order-dependent
    prev = sk._load_pad_rules().get(plat)
    sk.set_pad_rules(plat, [{"n": 4096, "k": 10, "k_pad": 32}])
    try:
        assert sk._pad_k(4096, 10) == 32
        assert sk._pad_k(5000, 10) == 32      # within x1.25
        assert sk._pad_k(4096, 11) == 11      # k must match exactly
        assert sk._pad_k(16384, 10) == 10     # outside the window
        # nearest-width rule wins; k_pad clamps to the row width
        sk.set_pad_rules(plat, [{"n": 4096, "k": 10, "k_pad": 32},
                                {"n": 6144, "k": 10, "k_pad": 16},
                                {"n": 64, "k": 10, "k_pad": 4096}])
        assert sk._pad_k(5800, 10) == 16
        assert sk._pad_k(64, 10) == 64

        rng = np.random.default_rng(3)
        x = rng.standard_normal((8, 4100)).astype(np.float32)
        x[:, 50:60] = x[:, 40:50]  # duplicate values: tie behavior
        # the wiring, not just _pad_k: record the k DIRECT actually asks
        # lax.top_k for while tracing (k_pad is in the jit key, so this
        # trace is fresh even if (8, 4100) ran unpadded before)
        sk.set_pad_rules(plat, [{"n": 4096, "k": 10, "k_pad": 32}])
        asked = []
        real_top_k = jax.lax.top_k

        def recording_top_k(operand, kk):
            asked.append(kk)
            return real_top_k(operand, kk)

        jax.lax.top_k = recording_top_k
        try:
            v, i = select_k(x, 10, algo=SelectAlgo.DIRECT)
        finally:
            jax.lax.top_k = real_top_k
        assert 32 in asked, f"pad rule not applied (asked: {asked})"
        ref = np.argsort(x, 1, kind="stable")[:, :10]
        np.testing.assert_array_equal(np.asarray(i), ref)
        np.testing.assert_array_equal(
            np.asarray(v), np.take_along_axis(x, ref, 1))
    finally:
        sk.set_pad_rules(plat, prev)
    if prev is None:
        assert sk._pad_k(4096, 10) == 10


def test_platform_key_axon_maps_to_tpu(monkeypatch):
    """The axon tunnel registers backend name "axon" while devices report
    platform "tpu" — table lookups must treat them as one platform, else
    every measured tpu table silently fails to arm on chip."""
    import importlib

    import jax

    sk = importlib.import_module("raft_tpu.ops.select_k")
    monkeypatch.setattr(jax, "default_backend", lambda: "axon")
    assert sk._platform_key() == "tpu"
    # builtin tpu pad rule fires under the axon backend name — and
    # survives the shipped TOPK_PAD_tpu.json artifact, which measured
    # other widths but not the (4096, 10) cell (merge semantics:
    # artifact rules + builtins for unmeasured cells)
    assert sk._pad_k(4096, 10) == 32
    # a cell the artifact DID measure comes from the artifact
    assert sk._pad_k(8192, 10) == 16
    monkeypatch.setattr(jax, "default_backend", lambda: "cpu")
    assert sk._platform_key() == "cpu"
    assert sk._pad_k(4096, 10) == 10


def test_merge_pad_rules_builtin_survives_unmeasured_cells():
    """TOPK_PAD artifacts merge with the builtin pad table per (n, k)
    cell: a measured cell always wins (including k_pad == k "no pad"
    entries), a builtin survives when the artifact never measured its
    cell (ADVICE r5: wholesale replacement silently disarmed the n=4096
    builtin)."""
    import importlib

    sk = importlib.import_module("raft_tpu.ops.select_k")
    builtin = [{"n": 4096, "k": 10, "k_pad": 32},
               {"n": 2048, "k": 10, "k_pad": 32}]
    measured = [{"n": 2048, "k": 10, "k_pad": 10},   # measured: no pad
                {"n": 8192, "k": 10, "k_pad": 16}]
    merged = sk._merge_pad_rules(builtin, measured)
    cells = {(r["n"], r["k"]): r["k_pad"] for r in merged}
    assert cells[(2048, 10)] == 10   # measured overrides builtin
    assert cells[(8192, 10)] == 16   # measured-only cell kept
    assert cells[(4096, 10)] == 32   # unmeasured builtin survives
    assert len(merged) == 3
