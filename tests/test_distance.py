"""Pairwise-distance tests vs scipy (the reference's Python tests compare
against scipy/sklearn the same way — python/pylibraft/pylibraft/test/
test_distance.py)."""

import numpy as np
import pytest
import scipy.spatial.distance as scipy_dist

from raft_tpu.ops import DistanceType, pairwise_distance, row_norms_sq
from raft_tpu.ops.distance import resolve_metric, is_min_close

SCIPY_NAMES = {
    DistanceType.L2SqrtExpanded: "euclidean",
    DistanceType.L2Expanded: "sqeuclidean",
    DistanceType.L2SqrtUnexpanded: "euclidean",
    DistanceType.L2Unexpanded: "sqeuclidean",
    DistanceType.L1: "cityblock",
    DistanceType.Linf: "chebyshev",
    DistanceType.Canberra: "canberra",
    DistanceType.CosineExpanded: "cosine",
    DistanceType.CorrelationExpanded: "correlation",
    DistanceType.BrayCurtis: "braycurtis",
    DistanceType.JensenShannon: "jensenshannon",
}


@pytest.mark.parametrize("metric", sorted(SCIPY_NAMES, key=lambda m: m.value))
@pytest.mark.parametrize("shape", [(50, 40, 16), (33, 17, 130)])
def test_vs_scipy(metric, shape, rng):
    m, n, k = shape
    x = rng.standard_normal((m, k)).astype(np.float32)
    y = rng.standard_normal((n, k)).astype(np.float32)
    if metric == DistanceType.JensenShannon:
        x = np.abs(x) + 1e-3
        y = np.abs(y) + 1e-3
        x /= x.sum(1, keepdims=True)
        y /= y.sum(1, keepdims=True)
    got = np.asarray(pairwise_distance(x, y, metric=metric))
    want = scipy_dist.cdist(x, y, SCIPY_NAMES[metric])
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


def test_inner_product(rng):
    x = rng.standard_normal((20, 8)).astype(np.float32)
    y = rng.standard_normal((30, 8)).astype(np.float32)
    got = np.asarray(pairwise_distance(x, y, metric="inner_product"))
    np.testing.assert_allclose(got, x @ y.T, rtol=1e-5, atol=1e-5)


def test_minkowski(rng):
    x = rng.standard_normal((20, 8)).astype(np.float32)
    y = rng.standard_normal((30, 8)).astype(np.float32)
    got = np.asarray(pairwise_distance(x, y, metric="minkowski", metric_arg=3.0))
    want = scipy_dist.cdist(x, y, "minkowski", p=3.0)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_hellinger(rng):
    x = np.abs(rng.standard_normal((20, 8))).astype(np.float32)
    y = np.abs(rng.standard_normal((30, 8))).astype(np.float32)
    x /= x.sum(1, keepdims=True)
    y /= y.sum(1, keepdims=True)
    got = np.asarray(pairwise_distance(x, y, metric="hellinger"))
    inner = np.sqrt(x) @ np.sqrt(y).T
    want = np.sqrt(np.maximum(1 - inner, 0))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_kl_divergence(rng):
    x = np.abs(rng.standard_normal((20, 8))).astype(np.float32) + 1e-3
    y = np.abs(rng.standard_normal((30, 8))).astype(np.float32) + 1e-3
    x /= x.sum(1, keepdims=True)
    y /= y.sum(1, keepdims=True)
    got = np.asarray(pairwise_distance(x, y, metric="kl_divergence"))
    want = 0.5 * np.sum(
        x[:, None, :] * (np.log(x[:, None, :]) - np.log(y[None, :, :])), axis=-1
    )
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)


def test_hamming(rng):
    x = (rng.random((20, 16)) > 0.5).astype(np.float32)
    y = (rng.random((30, 16)) > 0.5).astype(np.float32)
    got = np.asarray(pairwise_distance(x, y, metric="hamming"))
    want = scipy_dist.cdist(x, y, "hamming")
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_russelrao(rng):
    x = (rng.random((20, 16)) > 0.5).astype(np.float32)
    y = (rng.random((30, 16)) > 0.5).astype(np.float32)
    got = np.asarray(pairwise_distance(x, y, metric="russelrao"))
    want = scipy_dist.cdist(x, y, "russellrao")
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_haversine():
    # London, Paris, NYC (lat, lon in radians)
    pts = np.radians(
        np.array([[51.5074, -0.1278], [48.8566, 2.3522], [40.7128, -74.0060]])
    ).astype(np.float32)
    d = np.asarray(pairwise_distance(pts, pts, metric="haversine"))
    earth_km = 6371.0
    # London-Paris ≈ 344 km
    assert abs(d[0, 1] * earth_km - 344) < 10
    assert abs(d[0, 2] * earth_km - 5570) < 60
    np.testing.assert_allclose(d, d.T, atol=1e-6)


def test_tiled_path_matches_direct(rng, res):
    """Force tiling by shrinking the workspace budget."""
    from raft_tpu import Resources

    x = rng.standard_normal((257, 33)).astype(np.float32)
    y = rng.standard_normal((119, 33)).astype(np.float32)
    small = Resources(workspace_limit_bytes=200_000)
    got = np.asarray(pairwise_distance(x, y, metric="l1", res=small))
    want = scipy_dist.cdist(x, y, "cityblock")
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_resolve_and_minclose():
    assert resolve_metric("euclidean") == DistanceType.L2SqrtExpanded
    assert resolve_metric(0) == DistanceType.L2Expanded
    assert is_min_close("euclidean")
    assert not is_min_close("inner_product")


def test_row_norms(rng):
    x = rng.standard_normal((10, 5)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(row_norms_sq(x)), (x * x).sum(1), rtol=1e-5
    )


def test_unsupported_dense_metric(rng):
    x = np.zeros((4, 4), np.float32)
    with pytest.raises(NotImplementedError):
        pairwise_distance(x, x, metric="jaccard")


# ---------------------------------------------------------------------------
# gram kernels (reference: distance/detail/kernels/kernel_matrices.cuh)

def test_gram_kernels_dense(rng):
    from raft_tpu.ops import kernels as K

    x = rng.standard_normal((20, 8)).astype(np.float32)
    y = rng.standard_normal((15, 8)).astype(np.float32)
    ip = x @ y.T

    np.testing.assert_allclose(np.asarray(K.linear_kernel(x, y)), ip,
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(
        np.asarray(K.polynomial_kernel(x, y, degree=3, gamma=0.5, coef0=1.0)),
        (0.5 * ip + 1.0) ** 3, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(
        np.asarray(K.tanh_kernel(x, y, gamma=0.5, coef0=0.1)),
        np.tanh(0.5 * ip + 0.1), rtol=1e-4, atol=1e-4)
    sq = ((x[:, None, :] - y[None, :, :]) ** 2).sum(-1)
    np.testing.assert_allclose(np.asarray(K.rbf_kernel(x, y, gamma=0.25)),
                               np.exp(-0.25 * sq), rtol=1e-4, atol=1e-4)


def test_gram_kernels_dispatch_and_sparse(rng):
    from raft_tpu.ops import kernels as K
    from raft_tpu.sparse.convert import dense_to_csr

    xd = rng.standard_normal((12, 10)).astype(np.float32)
    yd = rng.standard_normal((9, 10)).astype(np.float32)
    xd[rng.random(xd.shape) < 0.5] = 0.0
    yd[rng.random(yd.shape) < 0.5] = 0.0
    xs, ys = dense_to_csr(xd), dense_to_csr(yd)
    ip = xd @ yd.T

    # dispatch via KernelParams
    p = K.KernelParams(K.KernelType.POLYNOMIAL, degree=2, gamma=1.0, coef0=0.5)
    np.testing.assert_allclose(np.asarray(K.gram_matrix(xd, yd, p)),
                               (ip + 0.5) ** 2, rtol=1e-4, atol=1e-4)
    # CSR×dense, dense×CSR, CSR×CSR all agree with the dense result
    for a, b in ((xs, yd), (xd, ys), (xs, ys)):
        np.testing.assert_allclose(np.asarray(K.linear_kernel(a, b)), ip,
                                   rtol=1e-4, atol=1e-4)
    sq = ((xd[:, None, :] - yd[None, :, :]) ** 2).sum(-1)
    np.testing.assert_allclose(
        np.asarray(K.gram_matrix(xs, ys, K.KernelParams(K.KernelType.RBF,
                                                        gamma=0.1))),
        np.exp(-0.1 * sq), rtol=1e-4, atol=1e-4)


def test_masked_l2_nn_argmin(rng):
    from raft_tpu.ops.fused_l2_nn import masked_l2_nn_argmin

    m, n, k, g = 50, 40, 8, 4
    x = rng.standard_normal((m, k)).astype(np.float32)
    y = rng.standard_normal((n, k)).astype(np.float32)
    # groups of y rows given by end offsets (reference prefix-sum convention)
    group_idxs = np.array([10, 22, 31, 40], np.int32)
    adj = rng.random((m, g)) < 0.6
    adj[0] = False  # a row with no allowed group -> inf

    val, idx = masked_l2_nn_argmin(x, y, adj, group_idxs)
    d = ((x[:, None, :] - y[None, :, :]) ** 2).sum(-1)
    starts = np.r_[0, group_idxs[:-1]]
    group_of_y = np.zeros(n, np.int32)
    for gi, (s, e) in enumerate(zip(starts, group_idxs)):
        group_of_y[s:e] = gi
    allowed = adj[:, group_of_y]
    dm = np.where(allowed, d, np.inf)
    ref_val, ref_idx = dm.min(1), dm.argmin(1)
    np.testing.assert_array_equal(np.asarray(idx), ref_idx)
    has = np.isfinite(ref_val)
    np.testing.assert_allclose(np.asarray(val)[has], ref_val[has],
                               rtol=1e-4, atol=1e-4)
    assert np.isinf(np.asarray(val)[0])


def test_masked_l2_nn_tiled(rng):
    from raft_tpu.ops.fused_l2_nn import masked_l2_nn_argmin
    from raft_tpu import Resources

    m, n, k, g = 300, 64, 16, 2
    x = rng.standard_normal((m, k)).astype(np.float32)
    y = rng.standard_normal((n, k)).astype(np.float32)
    group_idxs = np.array([30, 64], np.int32)
    adj = rng.random((m, g)) < 0.7
    small = Resources(workspace_limit_bytes=64 * 1024)
    val, idx = masked_l2_nn_argmin(x, y, adj, group_idxs, res=small)
    d = ((x[:, None, :] - y[None, :, :]) ** 2).sum(-1)
    group_of_y = (np.arange(n)[:, None] >= group_idxs[None, :]).sum(1)
    dm = np.where(adj[:, group_of_y], d, np.inf)
    np.testing.assert_array_equal(np.asarray(idx), dm.argmin(1))
