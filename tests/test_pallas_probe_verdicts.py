"""pallas_probe --require-verdicts: the TPU-queue guard that an
artifact about to be committed actually routes scan_mode/merge_mode
auto — a missing or errored fused_wins row must fail loudly (exit 2 in
the tool), never ship as a silent always-XLA routing table."""

import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "tools"))
import pallas_probe  # noqa: E402

pytestmark = pytest.mark.fast

FULL = {"fused": {
    "brute_force": {"fused_wins": True, "pallas_ms": 1.0, "xla_ms": 2.0},
    "ivf_flat": {"fused_wins": False, "pallas_ms": 3.0, "xla_ms": 2.0},
    "ivf_pq": {"fused_wins": True},
    "ivf_scan": {"fused_wins": False},
    "l2_argmin": {"fused_wins": True},
    "cagra": {"fused_wins": True, "pallas_ms": 5.0, "xla_ms": 9.0},
    "merge_ring": {"fused_wins": True, "ring_ms": 1.0, "tree_ms": 2.0},
}}


def test_complete_artifact_passes():
    assert pallas_probe.missing_verdicts(FULL, on_tpu=True,
                                         mergeable_mesh=True) == []


def test_single_chip_host_does_not_require_merge_ring():
    art = {"fused": {k: v for k, v in FULL["fused"].items()
                     if k != "merge_ring"}}
    assert pallas_probe.missing_verdicts(art, on_tpu=True,
                                         mergeable_mesh=False) == []
    # ...but a pod host must land the merge row
    assert pallas_probe.missing_verdicts(art, on_tpu=True,
                                         mergeable_mesh=True) == \
        ["merge_ring"]


def test_missing_and_errored_rows_are_flagged():
    art = {"fused": dict(FULL["fused"])}
    del art["fused"]["ivf_pq"]                       # absent row
    art["fused"]["merge_ring"] = {                   # errored row
        "pallas_error": "MosaicError: ...", "fused_wins": False}
    art["fused"]["l2_argmin"] = {"derived_from": "x"}  # verdict-less row
    got = pallas_probe.missing_verdicts(art, on_tpu=True,
                                        mergeable_mesh=True)
    assert got == ["ivf_pq", "l2_argmin", "merge_ring"]


def test_probe_missing_cagra_row_is_incomplete():
    """Schema v3: the fused beam-search engine is a routing family — an
    artifact without its row (e.g. a queue window that died before the
    cagrafuse step) must not pass --require-verdicts."""
    art = {"fused": {k: v for k, v in FULL["fused"].items()
                     if k != "cagra"}}
    assert pallas_probe.missing_verdicts(
        art, on_tpu=True, mergeable_mesh=True) == ["cagra"]


def test_off_tpu_host_can_never_mint_verdicts():
    # scan_mode="pallas" silently falls back off-TPU, so even a
    # complete-looking artifact is XLA-vs-XLA timings — all required
    got = pallas_probe.missing_verdicts(FULL, on_tpu=False,
                                        mergeable_mesh=True)
    assert got == [*pallas_probe.REQUIRED_VERDICT_FAMILIES, "merge_ring"]
