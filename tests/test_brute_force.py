"""Brute-force kNN tests: exact agreement with a numpy oracle, tiling paths,
serialization round-trip (reference pattern: cpp/test/neighbors/
knn_brute_force.cu + ann fixtures' serialize round-trips)."""

import io

import numpy as np
import pytest

from raft_tpu import Resources
from raft_tpu.neighbors import brute_force
from raft_tpu.stats import neighborhood_recall


def _numpy_knn(queries, dataset, k, metric="sqeuclidean"):
    import scipy.spatial.distance as sd

    d = sd.cdist(queries, dataset, metric)
    idx = np.argsort(d, axis=1, kind="stable")[:, :k]
    return np.take_along_axis(d, idx, 1), idx


@pytest.mark.parametrize("metric,scipy_metric", [
    ("sqeuclidean", "sqeuclidean"),
    ("euclidean", "euclidean"),
    ("cosine", "cosine"),
])
def test_exact_recall(metric, scipy_metric, rng):
    db = rng.standard_normal((500, 32)).astype(np.float32)
    q = rng.standard_normal((40, 32)).astype(np.float32)
    dist, idx = brute_force.knn(q, db, k=10, metric=metric)
    want_dist, want_idx = _numpy_knn(q, db, 10, scipy_metric)
    # tie-tolerant recall: fp32 near-ties can flip ranks at the k boundary
    recall = float(
        neighborhood_recall(
            np.asarray(idx), want_idx, np.asarray(dist), want_dist, eps=1e-4
        )
    )
    assert recall >= 0.999


def test_inner_product_maximizes(rng):
    db = rng.standard_normal((200, 16)).astype(np.float32)
    q = rng.standard_normal((10, 16)).astype(np.float32)
    dist, idx = brute_force.knn(q, db, k=5, metric="inner_product")
    ip = q @ db.T
    want = np.argsort(-ip, axis=1)[:, :5]
    assert float(neighborhood_recall(np.asarray(idx), want)) >= 0.999
    # returned "distances" are the (descending) inner products
    assert np.all(np.diff(np.asarray(dist), axis=1) <= 1e-5)


def test_tiled_matches_untiled(rng):
    db = rng.standard_normal((1000, 24)).astype(np.float32)
    q = rng.standard_normal((30, 24)).astype(np.float32)
    small = Resources(workspace_limit_bytes=1_000_000)
    d1, i1 = brute_force.knn(q, db, k=7, res=small)
    d2, i2 = brute_force.knn(q, db, k=7)
    np.testing.assert_allclose(np.asarray(d1), np.asarray(d2), rtol=1e-4, atol=1e-5)
    assert float(neighborhood_recall(np.asarray(i1), np.asarray(i2))) >= 0.999


def test_k_clamped_to_size(rng):
    db = rng.standard_normal((5, 8)).astype(np.float32)
    q = rng.standard_normal((3, 8)).astype(np.float32)
    d, i = brute_force.search(brute_force.build(db), q, k=10)
    assert d.shape == (3, 5)


def test_serialize_roundtrip(rng):
    db = rng.standard_normal((100, 16)).astype(np.float32)
    q = rng.standard_normal((10, 16)).astype(np.float32)
    idx = brute_force.build(db, metric="euclidean")
    buf = io.BytesIO()
    brute_force.serialize(idx, buf)
    buf.seek(0)
    idx2 = brute_force.deserialize(buf)
    d1, i1 = brute_force.search(idx, q, 5)
    d2, i2 = brute_force.search(idx2, q, 5)
    np.testing.assert_allclose(np.asarray(d1), np.asarray(d2), rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))


def test_bitset_filter(rng):
    from raft_tpu.core.bitset import Bitset

    db = rng.standard_normal((200, 16)).astype(np.float32)
    q = rng.standard_normal((20, 16)).astype(np.float32)
    mask = rng.random(200) < 0.5
    bs = Bitset.from_mask(mask)
    idx = brute_force.build(db, metric="sqeuclidean")
    d, i = brute_force.search(idx, q, 10, filter=bs)
    i = np.asarray(i)
    assert mask[i].all()  # only allowed rows returned
    ref = ((q[:, None, :] - db[None, :, :]) ** 2).sum(-1)
    ref = np.where(mask[None, :], ref, np.inf)
    np.testing.assert_array_equal(i[:, 0], ref.argmin(1))


@pytest.mark.parametrize("dt", ["int8", "uint8", "bfloat16"])
def test_narrow_dtypes(dt, rng):
    import jax.numpy as jnp

    if dt == "bfloat16":
        db = jnp.asarray(rng.standard_normal((500, 16)), jnp.bfloat16)
        q = jnp.asarray(rng.standard_normal((50, 16)), jnp.bfloat16)
        ref_db = np.asarray(db, np.float32)
        ref_q = np.asarray(q, np.float32)
    else:
        lo = -120 if dt == "int8" else 0
        db = rng.integers(lo, 120, (500, 16)).astype(dt)
        q = rng.integers(lo, 120, (50, 16)).astype(dt)
        ref_db = db.astype(np.float32)
        ref_q = q.astype(np.float32)
    _, i = brute_force.knn(q, db, 5, metric="sqeuclidean")
    ref = ((ref_q[:, None, :] - ref_db[None, :, :]) ** 2).sum(-1)
    np.testing.assert_array_equal(np.asarray(i)[:, 0], ref.argmin(1))


@pytest.mark.parametrize("metric", ["sqeuclidean", "euclidean", "cosine",
                                    "inner_product"])
def test_fast_scan_bf16_refined(metric, rng):
    """bf16 single-pass scan + exact fp32 re-rank: near-perfect recall and
    exact distances on the returned candidates."""
    from raft_tpu.stats import neighborhood_recall

    db = rng.standard_normal((3000, 64)).astype(np.float32)
    q = rng.standard_normal((100, 64)).astype(np.float32)
    idx = brute_force.build(db, metric=metric)
    d_f, i_f = brute_force.search(idx, q, 10, scan_dtype="bfloat16")
    d_e, i_e = brute_force.search(idx, q, 10)
    rec = float(neighborhood_recall(np.asarray(i_f), np.asarray(i_e)))
    assert rec >= 0.99
    # wherever the fast path picked the true neighbor, its distance is exact
    same = np.asarray(i_f) == np.asarray(i_e)
    np.testing.assert_allclose(np.asarray(d_f)[same], np.asarray(d_e)[same],
                               rtol=1e-5, atol=1e-5)


def test_fast_scan_tiled_and_filtered(rng):
    from raft_tpu.core.bitset import Bitset
    from raft_tpu.core.resources import Resources

    db = rng.standard_normal((2500, 32)).astype(np.float32)
    q = rng.standard_normal((64, 32)).astype(np.float32)
    mask = rng.random(2500) < 0.6
    bs = Bitset.from_mask(mask)
    # tiny workspace forces multiple db tiles through the merge path
    res = Resources(workspace_limit_bytes=2 << 20)
    idx = brute_force.build(db, metric="sqeuclidean", res=res)
    d, i = brute_force.search(idx, q, 8, filter=bs, res=res,
                              scan_dtype="bfloat16")
    i = np.asarray(i)
    assert mask[i].all()
    ref = ((q[:, None, :] - db[None, :, :]) ** 2).sum(-1)
    ref = np.where(mask[None, :], ref, np.inf)
    np.testing.assert_array_equal(i[:, 0], ref.argmin(1))


def test_batch_k_query_iterator(rng):
    """Batched neighbor iteration: concatenated batches equal one wide
    search (reference: make_batch_k_query)."""
    db = rng.standard_normal((500, 16)).astype(np.float32)
    q = rng.standard_normal((20, 16)).astype(np.float32)
    idx = brute_force.build(db, metric="sqeuclidean")
    batches = []
    it = brute_force.make_batch_k_query(idx, q, batch_size=7)
    for _ in range(3):
        d, i = next(it)
        assert i.shape == (20, 7)
        batches.append(np.asarray(i))
    d_ref, i_ref = brute_force.search(idx, q, 21)
    np.testing.assert_array_equal(np.concatenate(batches, 1),
                                  np.asarray(i_ref))
    # exhausting the iterator covers the whole dataset exactly once
    total = 21 + sum(i.shape[1] for _, i in it)
    assert total == 500


def test_choose_tiles_balanced():
    """The tile grid splits the db evenly: rounding down to the lane
    multiple used to give n_db=10000 a second, 99.8%-padding tile
    (2x scan work on the headline shape)."""
    from raft_tpu.neighbors.brute_force import _choose_tiles
    from raft_tpu.utils.shape import cdiv

    for n_db in (999, 10_000, 131_073, 200_000, 1_000_000):
        _, db_tile = _choose_tiles(10_000, n_db, 128, 10, 2 << 30)
        n_tiles = cdiv(n_db, db_tile)
        assert n_tiles * db_tile - n_db < 128 * n_tiles + 8, \
            (n_db, db_tile, n_tiles)
        if n_tiles > 1:
            assert db_tile % 128 == 0
