"""Checkpoint integrity plumbing (core.serialize format v2): per-record
crc framing, the footer, atomic writes, and overflow-bearing index
round-trips through the framed writer."""

import io
import os
import struct

import numpy as np
import pytest

from raft_tpu.core import serialize as ser
from raft_tpu.core.errors import IntegrityError
from raft_tpu.core.resources import Resources
from raft_tpu.neighbors import ivf_flat, ivf_pq


def test_framed_roundtrip_and_spans(tmp_path):
    path = str(tmp_path / "f")
    with ser.writer_for(path) as stream:
        w = ser.IndexWriter(stream, "t", 1)
        w.scalar(7, "<i4").string("hello").array(np.arange(6).reshape(2, 3))
        w.finish()
    with ser.reader_for(path) as stream:
        r = ser.IndexReader(stream, "t", 1, name=path)
        assert r.fmt_version == 2
        assert r.scalar() == 7
        assert r.string() == "hello"
        np.testing.assert_array_equal(r.array(),
                                      np.arange(6).reshape(2, 3))
        r.finish()
    spans = ser.record_spans(path)
    assert len(spans) == 4  # 3 records + footer
    assert all(n > 0 for _, n in spans)


def test_scalar_bad_dtype_tag():
    """A garbage dtype tag must be a typed IntegrityError, not a numpy
    TypeError deep in a restore stack."""
    buf = io.BytesIO()
    buf.write(struct.pack("<B", 4))
    buf.write(b"\xff\xfe\x00Z")  # not a dtype, not even decodable
    buf.seek(0)
    with pytest.raises(IntegrityError) as ei:
        ser.deserialize_scalar(buf)
    assert ei.value.reason in ("corrupt", "truncated")


def test_scalar_truncated():
    buf = io.BytesIO()
    ser.serialize_scalar(buf, 123, "<i8")
    raw = buf.getvalue()
    with pytest.raises(IntegrityError) as ei:
        ser.deserialize_scalar(io.BytesIO(raw[:-3]))
    assert ei.value.reason == "truncated"


def test_missing_footer_reads_truncated(tmp_path):
    """A writer that never called finish() (crash before the footer) must
    not read as complete."""
    path = str(tmp_path / "nofooter")
    with ser.writer_for(path) as stream:
        w = ser.IndexWriter(stream, "t", 1)
        w.scalar(1, "<i4")
        # no finish()
    with ser.reader_for(path) as stream:
        r = ser.IndexReader(stream, "t", 1, name=path)
        assert r.scalar() == 1
        with pytest.raises(IntegrityError) as ei:
            r.finish()
    assert ei.value.reason == "truncated"
    assert ei.value.path == path


def test_extra_records_rejected_by_footer(tmp_path):
    """Footer count mismatch (reader consumed fewer records than written —
    a reader/writer field-set skew) is corrupt, not silently ignored."""
    path = str(tmp_path / "skew")
    with ser.writer_for(path) as stream:
        w = ser.IndexWriter(stream, "t", 1)
        w.scalar(1, "<i4").scalar(2, "<i4")
        w.finish()
    with ser.reader_for(path) as stream:
        r = ser.IndexReader(stream, "t", 1, name=path)
        assert r.scalar() == 1
        with pytest.raises(IntegrityError) as ei:
            r.finish()  # one record early: next frame is not the footer
    assert ei.value.reason == "corrupt"


def test_atomic_write_failure_leaves_nothing(tmp_path):
    path = str(tmp_path / "atomic")
    with pytest.raises(RuntimeError, match="boom"), \
            ser.writer_for(path) as stream:
        stream.write(b"partial bytes")
        raise RuntimeError("boom")
    assert not os.path.exists(path)
    assert os.listdir(tmp_path) == []  # tmp file cleaned up too


def test_atomic_write_preserves_previous_checkpoint(tmp_path):
    path = str(tmp_path / "keep")
    with ser.writer_for(path) as stream:
        stream.write(b"good v1")
    with pytest.raises(RuntimeError), ser.writer_for(path) as stream:
        stream.write(b"half of v2")
        raise RuntimeError("crash mid-serialize")
    with open(path, "rb") as f:
        assert f.read() == b"good v1"  # old checkpoint intact


def _overflow_dataset(rng, n, dim):
    """One hot blob coarse k-means can't split at small n_lists: with a
    tight list_pad_expansion the hot lists' tails spill to the overflow
    block."""
    n_hot = n // 2
    hot = rng.standard_normal((n_hot, dim)).astype(np.float32) * 0.05
    rest = rng.standard_normal((n - n_hot, dim)).astype(np.float32) * 0.05
    rest += rng.standard_normal((n - n_hot, 1)).astype(np.float32) * 3.0
    out = np.concatenate([hot, rest])
    rng.shuffle(out)
    return out


def test_ivf_pq_overflow_roundtrip(tmp_path):
    rng = np.random.default_rng(21)
    x = _overflow_dataset(rng, 4096, 16)
    q = x[:16] + 0.01 * rng.standard_normal((16, 16)).astype(np.float32)
    res = Resources(seed=0)
    idx = ivf_pq.build(x, ivf_pq.IndexParams(n_lists=16, pq_dim=8,
                                             kmeans_n_iters=3,
                                             list_pad_expansion=1.01),
                       res=res)
    assert idx.overflow_indices is not None
    assert int(np.sum(np.asarray(idx.overflow_indices) >= 0)) > 0
    path = str(tmp_path / "pq_over")
    ivf_pq.serialize(idx, path)
    idx2 = ivf_pq.deserialize(path, res=res)
    sp = ivf_pq.SearchParams(n_probes=32, scan_mode="lut")
    d0, i0 = ivf_pq.search(idx, q, 10, sp, res=res)
    d1, i1 = ivf_pq.search(idx2, q, 10, sp, res=res)
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
    np.testing.assert_allclose(np.asarray(d0), np.asarray(d1), rtol=1e-6)


def test_ivf_flat_overflow_roundtrip(tmp_path):
    rng = np.random.default_rng(22)
    x = _overflow_dataset(rng, 4096, 16)
    q = x[:16] + 0.01 * rng.standard_normal((16, 16)).astype(np.float32)
    res = Resources(seed=0)
    idx = ivf_flat.build(x, ivf_flat.IndexParams(n_lists=16,
                                                 kmeans_n_iters=3,
                                                 list_pad_expansion=1.01),
                         res=res)
    assert idx.overflow_indices is not None
    assert int(np.sum(np.asarray(idx.overflow_indices) >= 0)) > 0
    path = str(tmp_path / "flat_over")
    ivf_flat.serialize(idx, path)
    idx2 = ivf_flat.deserialize(path, res=res)
    sp = ivf_flat.SearchParams(n_probes=32)
    d0, i0 = ivf_flat.search(idx, q, 10, sp, res=res)
    d1, i1 = ivf_flat.search(idx2, q, 10, sp, res=res)
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
    np.testing.assert_allclose(np.asarray(d0), np.asarray(d1), rtol=1e-6)


def test_index_file_flip_byte_is_typed(tmp_path):
    """Single-chip index files get the same typed corruption detection as
    sharded rank files."""
    from raft_tpu.testing import faults

    rng = np.random.default_rng(23)
    x = rng.standard_normal((512, 16)).astype(np.float32)
    res = Resources(seed=0)
    idx = ivf_flat.build(x, ivf_flat.IndexParams(n_lists=8,
                                                 kmeans_n_iters=2), res=res)
    path = str(tmp_path / "flat")
    ivf_flat.serialize(idx, path)
    faults.flip_record_byte(path, 5)
    with pytest.raises(IntegrityError) as ei:
        ivf_flat.deserialize(path, res=res)
    assert ei.value.reason == "corrupt"
    assert ei.value.path == path
    assert ei.value.record == 5
