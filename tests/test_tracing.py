"""core/tracing.py — the R006 scope primitive (CPU-checked).

``range`` must behave identically as a context manager and a decorator
(the reference's RAII type vs its FUNC_RANGE macro), nest, re-enter, and
never swallow exceptions; the same instance is shared by every call of a
decorated entry point, so re-entrancy is not optional."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.core import tracing

pytestmark = pytest.mark.fast


def test_range_as_context_manager():
    with tracing.range("test.scope") as r:
        assert r.name == "test.scope"
        x = jnp.arange(4.0)
    assert float(x.sum()) == 6.0


def test_range_as_decorator_preserves_metadata():
    @tracing.range("test.decorated")
    def payload(a, b=2):
        """payload doc"""
        return a + b

    assert payload.__name__ == "payload"
    assert payload.__doc__ == "payload doc"
    assert payload(3) == 5
    assert payload(3, b=4) == 7


def test_exceptions_propagate_from_both_forms():
    r = tracing.range("test.raises")
    with pytest.raises(ValueError, match="inner"), r:
        raise ValueError("inner")

    @tracing.range("test.raises_deco")
    def boom():
        raise KeyError("deco")

    with pytest.raises(KeyError):
        boom()
    # the scope stack fully unwound — the instance is reusable
    with r:
        pass
    assert r._stack == []


def test_nesting_and_reentrancy():
    outer = tracing.range("test.outer")
    with outer:
        # same instance re-entered (recursive decorated function)
        with tracing.range("test.inner"), outer:
            assert len(outer._stack) == 2
        assert len(outer._stack) == 1
    assert outer._stack == []


def test_recursive_decorated_function():
    @tracing.range("test.recursive")
    def fact(n):
        return 1 if n <= 1 else n * fact(n - 1)

    assert fact(5) == 120


def test_range_inside_jit_names_the_hlo():
    def fn(x):
        with tracing.range("jitscope"):
            y = x * 2.0
            return y + 1.0

    np.testing.assert_allclose(np.asarray(jax.jit(fn)(jnp.ones(3))), 3.0)
    # named_scope survives into the compiled HLO op names — that is what
    # xprof reads, so it is the property worth pinning
    text = jax.jit(fn).lower(jnp.ones(3)).compile().as_text()
    assert "jitscope" in text


def test_annotate_defaults_to_qualname():
    @tracing.annotate()
    def named_by_default():
        return 7

    assert named_by_default() == 7

    @tracing.annotate("explicit.name")
    def named_explicitly():
        return 8

    assert named_explicitly() == 8
