"""Cross-host fleet tests: remote replicas over host_p2p, the closed
autoscale loop, and partition/split-brain chaos (docs/serving.md
"Remote fleet").

The invariants pinned here are the ISSUE 18 acceptance criteria:

- the wire codec round-trips headers + arrays bit-for-bit, and the
  typed-error table reconstructs the SAME exception classes on the
  proxy side (closed vocabulary, unknown kinds degrade to the typed
  retryable ``BatchFailed`` — never untyped);
- every transport failure classifies into the closed kind vocabulary
  by isinstance over the exception CHAIN and maps into the fleet's
  retryability table (refused → ``ReplicaStarting``, drained →
  ``EngineStopped``, anything else → ``BatchFailed`` with the original
  error on ``__cause__``);
- a loopback RemoteReplica serves results bit-identical to its engine,
  the rider's deadline rides the wire and is enforced remotely, health
  piggybacks on every reply, and the replica's own metrics text comes
  back through the ``scrape`` op (one-target aggregation);
- under a network partition the router routes EVERY request to the
  surviving sibling with zero untyped failures, the proxy's link
  verdict — not the replica's self-report — takes the severed replica
  out of quorum (split-brain authority rule), and the heal re-admits
  it through the existing breaker-probe path;
- the autoscaler's hysteresis law: scale-up only after a full
  sustained window (or immediately on fast-burn), scale-down only
  after the full cooldown, blocked decisions emit typed reasons, and
  lifecycle counters reconcile 1:1 with ``kind="autoscale"`` spans;
- a real ``replica_main`` child killed with SIGKILL mid-load yields
  exact typed accounting: ``submitted == sum(outcomes)`` and one
  ``kind="fleet"`` span per request (the CI faults-job smoke);
- the partition/heal race windows hold across >= 100 amplified
  interleave seeds (slow tier).
"""

import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from raft_tpu import serving
from raft_tpu.obs import metrics as obs_metrics
from raft_tpu.obs.spans import ListSink
from raft_tpu.parallel.host_p2p import HostP2P, PeerDrained
from raft_tpu.serving import remote
from raft_tpu.serving.autoscaler import Autoscaler, AutoscalerConfig
from raft_tpu.serving.engine import Engine, EngineConfig
from raft_tpu.serving.remote import (RemoteReplica, classify_transport,
                                     decode_error, decode_message,
                                     encode_error, encode_message,
                                     map_transport_error)
from raft_tpu.serving.replica_main import _ReplicaServer, build_searcher
from raft_tpu.testing import faults

pytestmark = pytest.mark.fast

DIM = 8
K = 5


def _ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _spec(seed=1, rows=256):
    return {"family": "brute_force", "dim": DIM, "rows": rows,
            "seed": seed}


def _reconcile(fleet):
    oc = fleet.stats.outcome_counts()
    resolved = sum(v for k, v in oc.items() if k != "submitted")
    assert oc["submitted"] == resolved, f"silent loss: {oc}"
    return oc


# ------------------------------------------------------------- wire codec
def test_codec_roundtrip_header_and_arrays():
    d = np.arange(10, dtype=np.float32).reshape(2, 5)
    i = np.arange(10, dtype=np.int64).reshape(2, 5) * 7
    hdr = {"op": "search", "k": 5, "cid": 1 << 21, "nested": {"a": 1}}
    header, arrays = decode_message(encode_message(hdr, d, i))
    assert header["op"] == "search" and header["k"] == 5
    assert header["cid"] == 1 << 21 and header["nested"] == {"a": 1}
    assert len(arrays) == 2
    np.testing.assert_array_equal(arrays[0], d)
    np.testing.assert_array_equal(arrays[1], i)
    assert arrays[0].dtype == np.float32 and arrays[1].dtype == np.int64


def test_codec_zero_arrays_and_empty_array():
    header, arrays = decode_message(encode_message({"op": "health"}))
    assert header["op"] == "health" and arrays == []
    header, arrays = decode_message(
        encode_message({"op": "x"}, np.empty((0, 4), np.float32)))
    assert arrays[0].shape == (0, 4)


def test_error_table_reconstructs_typed_classes():
    """Closed error-kind table: the proxy resurrects the SAME typed
    class the remote engine raised, so the router's retryability table
    cannot tell a remote replica from a local one."""
    cases = [
        (serving.DeadlineExceeded("late"), serving.DeadlineExceeded),
        (serving.QueueFull("full"), serving.QueueFull),
        (serving.Overloaded("shed"), serving.Overloaded),
        (serving.CircuitOpen("open"), serving.CircuitOpen),
        (serving.EngineStopped("gone"), serving.EngineStopped),
        (serving.BatchFailed("bad"), serving.BatchFailed),
    ]
    for exc, cls in cases:
        out = decode_error(encode_error(exc))
        assert type(out) is cls, (exc, out)
    # unknown kinds degrade TYPED and retryable, never silently
    out = decode_error({"error_kind": "???", "error_type": "Weird",
                        "message": "m"})
    assert isinstance(out, serving.BatchFailed)
    assert serving.is_retryable(out)


def test_classify_transport_closed_vocabulary():
    """classify_transport works by isinstance over the __cause__ chain
    (poisoned-stream wrappers carry the original error there), and
    every verdict is in the closed kind vocabulary."""
    import errno

    refused = ConnectionRefusedError(111, "refused")
    poisoned = ConnectionError("send stream poisoned")
    poisoned.__cause__ = refused
    unreach = OSError(errno.EHOSTUNREACH, "unreachable")
    cases = [
        (PeerDrained("bye"), "drained"),
        (refused, "refused"),
        (poisoned, "refused"),        # the chain, not the wrapper
        (unreach, "refused"),         # partitioned: EHOSTUNREACH
        (TimeoutError("no reply"), "reply_timeout"),
        (ConnectionResetError("rst"), "eof"),
        (OSError("generic"), "eof"),
        (RuntimeError("?"), "other"),
    ]
    for exc, kind in cases:
        assert classify_transport(exc) == kind, (exc, kind)
        assert kind in remote.TRANSPORT_FAILURE_KINDS
    # a cycle in the chain must not hang the walker
    a, b = ConnectionError("a"), ConnectionError("b")
    a.__cause__, b.__cause__ = b, a
    assert classify_transport(a) == "eof"


def test_map_transport_error_typed_and_chained():
    """Transport failures map into the fleet's retryability table and
    always chain the original error on __cause__."""
    refused = ConnectionRefusedError(111, "refused")
    out = map_transport_error(refused, "r1")
    assert isinstance(out, serving.ReplicaStarting)
    assert serving.is_retryable(out) and out.__cause__ is refused
    drained = PeerDrained("bye")
    out = map_transport_error(drained, "r1")
    assert isinstance(out, serving.EngineStopped)
    assert out.__cause__ is drained
    eof = ConnectionResetError("rst")
    out = map_transport_error(eof, "r1")
    assert isinstance(out, serving.BatchFailed)
    assert out.__cause__ is eof and serving.is_retryable(out)


# ------------------------------------------------------ loopback RPC path
@pytest.fixture()
def loopback():
    """One real engine behind a _ReplicaServer on rank 1, a RemoteReplica
    proxy on rank 0 — the whole wire path in-process."""
    p0, p1 = _ports(2)
    peers = [("127.0.0.1", p0), ("127.0.0.1", p1)]
    eng = Engine(build_searcher(_spec()),
                 EngineConfig(max_batch=4, max_wait_us=1000)).start()
    ep1 = HostP2P(rank=1, size=2, peers=peers, timeout=30,
                  peer_grace=0.5)
    server = _ReplicaServer(eng, ep1, frontend=0)
    st = threading.Thread(target=server.run, daemon=True)
    st.start()
    ep0 = HostP2P(rank=0, size=2, peers=peers, timeout=30,
                  peer_grace=0.5)
    proxy = RemoteReplica(ep0, peer=1, dim=DIM, name="r1",
                          rpc_timeout_s=10.0, rpc_slack_s=1.0).start()
    yield eng, server, proxy, ep0, ep1
    proxy.stop(drain=False)
    server._stop.set()
    eng.stop(drain=False)
    ep0.close()
    ep1.close()


def test_loopback_search_bit_identical(loopback):
    """A remote search returns EXACTLY what the engine behind it would
    return locally — the proxy adds transport, not approximation."""
    eng, server, proxy, *_ = loopback
    rng = np.random.default_rng(0)
    for _ in range(5):
        q = rng.standard_normal(DIM).astype(np.float32)
        d, i = proxy.submit(q, K, deadline_ms=5000).result(timeout=20)
        d2, i2 = eng.submit(q, K).result(timeout=20)
        np.testing.assert_array_equal(np.asarray(d), np.asarray(d2))
        np.testing.assert_array_equal(np.asarray(i), np.asarray(i2))


def test_loopback_deadline_rides_the_wire(loopback):
    """A microscopic deadline_ms must shed typed DeadlineExceeded — the
    REMOTE engine enforces the remaining budget, exactly like a local
    replica's shed path."""
    _, _, proxy, *_ = loopback
    q = np.zeros(DIM, np.float32)
    with pytest.raises(serving.DeadlineExceeded):
        proxy.submit(q, K, deadline_ms=0.01).result(timeout=20)


def test_loopback_health_piggyback_and_scrape(loopback):
    """Every reply piggybacks engine health (the proxy's cache is as
    fresh as the last reply), and the scrape op returns the replica's
    own Prometheus families — the one-target aggregation input."""
    _, _, proxy, *_ = loopback
    q = np.zeros(DIM, np.float32)
    proxy.submit(q, K, deadline_ms=5000).result(timeout=20)
    h = proxy.health()
    assert h["link"] == "up" and h["replica"] == "r1"
    assert h["status"] in ("ok", "degraded")
    assert proxy.stats.queue_wait_p99_s() >= 0.0
    text = proxy.scrape(timeout=10)
    assert "raft_tpu_serving" in text


def test_loopback_reset_samples_windows_remote_pressure(loopback):
    """The reset_samples op re-baselines the REMOTE latency window over
    the wire: afterwards the piggybacked windowed p99 (the autoscale
    pressure numerator) reads 0.0 until new batches complete, while the
    cumulative p99 keeps its history — the signal the load driver
    needs so pressure can fall when offered load falls."""
    _, _, proxy, *_ = loopback
    rng = np.random.default_rng(3)
    for _ in range(8):
        proxy.submit(rng.standard_normal(DIM).astype(np.float32),
                     K, deadline_ms=5000).result(timeout=20)
    assert proxy.stats.queue_wait_p99_s() > 0.0
    assert proxy.stats.queue_wait_p99_window_s() > 0.0
    assert proxy.reset_samples(timeout=10) is True
    # any reply refreshes the piggyback; scrape is a synchronous RPC
    proxy.scrape(timeout=10)
    assert proxy.stats.queue_wait_p99_window_s() == 0.0
    assert proxy.stats.queue_wait_p99_s() > 0.0
    # the view's delegate reaches the same wire path
    proxy.stats.reset_samples()


def test_loopback_graceful_stop_maps_to_engine_stopped(loopback):
    """After a stop RPC the replica announces a drain frame; the
    proxy's in-flight and later requests fail typed EngineStopped (the
    drained mapping), never untyped."""
    eng, server, proxy, *_ = loopback
    q = np.zeros(DIM, np.float32)
    proxy.submit(q, K, deadline_ms=5000).result(timeout=20)
    proxy.stop(drain=True)
    with pytest.raises(serving.EngineStopped):
        proxy.submit(q, K)


def test_fleet_scrape_appends_p2p_families():
    """Satellite: the 8 per-peer host_p2p counters live on the global
    registry; a fleet scraping a PRIVATE registry still surfaces them
    on its one /metrics target (extra_text append)."""
    sink = ListSink()
    reg = obs_metrics.Registry()
    cfg = serving.FleetConfig(quorum=1, span_sink=sink, registry=reg)
    fleet = serving.Fleet.from_searchers(
        [build_searcher(_spec())],
        engine_config=serving.EngineConfig(max_batch=4, max_wait_us=1000),
        config=cfg)
    with fleet:
        fleet.submit(np.zeros(DIM, np.float32), K).result(timeout=20)
        ms = fleet.serve_metrics(port=0)
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{ms.port}/metrics", timeout=10
        ).read().decode()
    assert "raft_tpu_fleet_requests_total" in body   # private registry
    assert "raft_tpu_p2p_messages_sent_total" in body  # global, appended
    # only the p2p families are appended — not the global serving ones
    # (those would double-count against the private registry's copies)
    assert body.count("# TYPE raft_tpu_fleet_requests_total") == 1


def test_fleet_metrics_routes_remote_replica_scrape(loopback):
    """Satellite: the remote replica's OWN Prometheus families (they
    live in the replica process's registry) are reachable through the
    fleet's single server at /metrics/replica/<name> — a scrape-op
    passthrough, not an inline merge (merging would duplicate family
    declarations). Unknown names and local replicas 404."""
    eng_r, server, proxy, *_ = loopback
    eng_l = Engine(build_searcher(_spec()),
                   EngineConfig(max_batch=4, max_wait_us=1000))
    fleet = serving.Fleet(
        [eng_l, proxy], names=["local0", "r1"],
        config=serving.FleetConfig(quorum=1,
                                   registry=obs_metrics.Registry()))
    try:
        fleet.start()
        fleet.submit(np.zeros(DIM, np.float32), K).result(timeout=20)
        ms = fleet.serve_metrics(port=0)
        url = f"http://127.0.0.1:{ms.port}"
        body = urllib.request.urlopen(
            f"{url}/metrics/replica/r1", timeout=10).read().decode()
        assert "raft_tpu_serving_requests_total" in body
        for bad in ("/metrics/replica/ghost", "/metrics/replica/local0"):
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(f"{url}{bad}", timeout=10)
            assert ei.value.code == 404
    finally:
        fleet.stop(drain=False)


# ------------------------------------------- partition / split-brain chaos
def test_partition_split_brain_and_heal_readmission():
    """The tentpole chaos invariant: partition the remote replica —
    every request resolves ok via the sibling (zero untyped), the
    PROXY's link verdict (not the replica's healthy self-report) takes
    it out of quorum, and the heal re-admits it through the router's
    breaker-probe path."""
    p0, p1 = _ports(2)
    peers = [("127.0.0.1", p0), ("127.0.0.1", p1)]
    eng_r = Engine(build_searcher(_spec()),
                   EngineConfig(max_batch=4, max_wait_us=1000)).start()
    ep1 = HostP2P(rank=1, size=2, peers=peers, timeout=30,
                  peer_grace=0.5)
    server = _ReplicaServer(eng_r, ep1, frontend=0)
    threading.Thread(target=server.run, daemon=True).start()
    ep0 = HostP2P(rank=0, size=2, peers=peers, timeout=30,
                  peer_grace=0.5)
    proxy = RemoteReplica(ep0, peer=1, dim=DIM, name="remote1",
                          rpc_timeout_s=3.0, rpc_slack_s=0.5)
    eng_l = Engine(build_searcher(_spec()),
                   EngineConfig(max_batch=4, max_wait_us=1000))
    sink = ListSink()
    fleet = serving.Fleet(
        [eng_l, proxy], names=["local0", "remote1"],
        config=serving.FleetConfig(quorum=1, span_sink=sink,
                                   probe_interval_s=0.2))
    rng = np.random.default_rng(0)
    qs = [rng.standard_normal(DIM).astype(np.float32)
          for _ in range(20)]
    try:
        fleet.start()
        for q in qs[:5]:
            fleet.submit(q, K).result(timeout=20)
        # ---- sever: one-sided cut, the replica itself stays healthy —
        # the split-brain shape (its self-report says ok; the router
        # must believe the proxy's link verdict instead)
        heal = faults.partition_hosts(ep0, 1)
        futs = [fleet.submit(q, K) for q in qs]
        for f in futs:
            assert f.exception(timeout=20) is None, f.exception()
        # the proxy notices on the first failed RPC; drive until it has
        deadline = time.monotonic() + 10
        while (proxy.health()["link"] == "up"
               and time.monotonic() < deadline):
            fleet.submit(qs[0], K).result(timeout=20)
            time.sleep(0.05)
        h = proxy.health()
        assert h["status"] == "unhealthy" and h["breaker"] == "open"
        assert h["link"] == "down" and h["running"]
        # split-brain authority: the severed-but-alive replica is OUT
        # of quorum even though its own engine reports healthy
        assert eng_r.health()["status"] == "ok"
        assert fleet.healthy_count() == 1
        _reconcile(fleet)
        # ---- heal: the probe path re-admits over the healed link
        heal()
        deadline = time.monotonic() + 20
        readmitted = False
        while time.monotonic() < deadline:
            for q in qs[:4]:
                fleet.submit(q, K).result(timeout=20)
            if proxy.health()["link"] == "up":
                readmitted = True
                break
            time.sleep(0.1)
        assert readmitted, "healed link never re-admitted"
        assert fleet.healthy_count() == 2
        oc = _reconcile(fleet)
        assert oc["submitted"] == len(sink.by_kind("fleet"))
    finally:
        fleet.stop(drain=False)
        server._stop.set()
        eng_r.stop(drain=False)
        ep0.close()
        ep1.close()


# --------------------------------------------------- autoscaler control law
class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def advance(self, dt):
        self.t += dt

    def __call__(self):
        return self.t


class _StubStats:
    def __init__(self):
        self.p99 = 0.0

    def queue_wait_p99_s(self):
        return self.p99


class _StubEngine:
    """Engine-shaped stub with a settable queue-wait p99 — drives the
    pressure signal without real load."""

    def __init__(self, dim=DIM):
        import types
        self.searcher = types.SimpleNamespace(dim=dim, coverage=1.0)
        self.batcher = []
        self.stats = _StubStats()
        self.autoscale_budget_ms = 50.0
        self._started = True

    def start(self):
        self._started = True
        return self

    def stop(self, drain=True, timeout=None):
        self._started = False

    def drain(self, timeout=None):
        return True

    def health(self):
        return {"status": "ok" if self._started else "unhealthy",
                "running": self._started, "breaker": "closed",
                "shedding": False, "queue_depth": 0, "coverage": 1.0,
                "n_batch_errors": 0, "n_hangs": 0}


def _autoscaled_fleet(clk, sink, max_replicas=3):
    fleet = serving.Fleet([_StubEngine()], names=["seed0"],
                          config=serving.FleetConfig(quorum=1),
                          clock=clk)
    fleet._started = True  # membership surface only; no batcher threads
    asc = Autoscaler(
        fleet, spawn=_StubEngine,
        config=AutoscalerConfig(min_replicas=1, max_replicas=max_replicas,
                                high_watermark=0.8, low_watermark=0.2,
                                up_window_s=5.0, down_window_s=30.0,
                                span_sink=sink),
        clock=clk)
    return fleet, asc


def _pressure(fleet, p99):
    for r in fleet.replicas:
        r.engine.stats.p99 = p99


def test_autoscaler_hysteresis_law():
    """The full law, single-stepped on a fake clock: scale-up only
    after the sustained window; one decision per window (re-arm);
    blocked at max with a typed reason; scale-down only after the FULL
    cooldown; counters reconcile 1:1 with spans."""
    clk, sink = _FakeClock(), ListSink()
    fleet, asc = _autoscaled_fleet(clk, sink)
    _pressure(fleet, 0.060)  # 60ms p99 / 50ms budget = 1.2
    asc.tick()
    clk.advance(2.0)
    asc.tick()
    assert len(fleet.replicas) == 1  # 2s sustained < 5s window
    clk.advance(3.5)
    asc.tick()
    assert len(fleet.replicas) == 2  # 5.5s sustained: spawn
    assert sink.by_kind("autoscale")[-1]["reason"] == "scale_up_pressure"
    # the window re-armed: one decision per window, never per tick
    _pressure(fleet, 0.060)
    asc.tick()
    clk.advance(5.5)
    asc.tick()
    assert len(fleet.replicas) == 3
    # at max: the decision is emitted, typed, not silently skipped
    _pressure(fleet, 0.060)
    asc.tick()
    clk.advance(6.0)
    asc.tick()
    assert len(fleet.replicas) == 3
    assert (sink.by_kind("autoscale")[-1]["reason"]
            == "blocked_max_replicas")
    # idle: 10s below the low watermark is NOT enough (30s cooldown)
    _pressure(fleet, 0.001)
    asc.tick()
    clk.advance(10.0)
    asc.tick()
    assert len(fleet.replicas) == 3, "retired before cooldown"
    clk.advance(25.0)
    asc.tick()
    assert len(fleet.replicas) == 2  # 35s below: retire ONE (LIFO)
    last = sink.by_kind("autoscale")[-1]
    assert last["reason"] == "scale_down_idle"
    assert last["replica"].startswith("scale")
    # lifecycle counters and spans reconcile 1:1
    lc = {ev: fleet.stats._lifecycle[ev].value
          for ev in ("added", "removed", "spawned", "retired",
                     "spawn_failed")}
    spans = sink.by_kind("autoscale")
    spawned = sum(1 for s in spans
                  if s["reason"].startswith("scale_up") and "replica" in s)
    retired = sum(1 for s in spans if s["reason"] == "scale_down_idle")
    assert lc["spawned"] == spawned and lc["retired"] == retired
    assert lc["added"] == lc["spawned"] and lc["removed"] == lc["retired"]
    assert lc["spawn_failed"] == 0


def test_autoscaler_fast_burn_scales_immediately():
    """An SLO fast-burn excursion skips the sustained window (burn is
    already a windowed signal) and stamps the slo/burn on the span."""
    clk, sink = _FakeClock(), ListSink()
    fleet, asc = _autoscaled_fleet(clk, sink)
    _pressure(fleet, 0.060)
    asc.on_fast_burn("availability", 20.0)
    asc.tick()  # no window wait
    assert len(fleet.replicas) == 2
    span = sink.by_kind("autoscale")[-1]
    assert span["reason"] == "scale_up_fast_burn"
    assert span["slo"] == "availability" and span["burn"] == 20.0


def test_autoscaler_spawn_failure_is_typed_decision():
    """A raising spawn() is a spawn_failed decision + lifecycle count,
    never an escaped exception out of the control loop."""
    clk, sink = _FakeClock(), ListSink()
    fleet, asc = _autoscaled_fleet(clk, sink)

    def bad_spawn():
        raise RuntimeError("container pull failed")

    asc.spawn = bad_spawn
    _pressure(fleet, 0.060)
    asc.tick()
    clk.advance(5.5)
    asc.tick()  # must not raise
    assert len(fleet.replicas) == 1
    span = sink.by_kind("autoscale")[-1]
    assert span["reason"] == "spawn_failed"
    assert "container pull failed" in span["error"]
    assert fleet.stats._lifecycle["spawn_failed"].value == 1


def test_autoscaler_scale_down_blocked_by_quorum():
    """remove_replica's quorum refusal surfaces as a typed
    blocked_quorum decision — the autoscaler never forces a fleet
    below quorum."""
    clk, sink = _FakeClock(), ListSink()
    fleet = serving.Fleet([_StubEngine(), _StubEngine()],
                          names=["seed0", "scale1"],
                          config=serving.FleetConfig(quorum=2),
                          clock=clk)
    fleet._started = True
    asc = Autoscaler(
        fleet, spawn=_StubEngine,
        config=AutoscalerConfig(min_replicas=1, max_replicas=3,
                                down_window_s=30.0, span_sink=sink),
        clock=clk)
    _pressure(fleet, 0.001)
    asc.tick()
    clk.advance(31.0)
    asc.tick()
    assert len(fleet.replicas) == 2  # refused, membership intact
    assert sink.by_kind("autoscale")[-1]["reason"] == "blocked_quorum"


def test_fleet_add_remove_replica_lifecycle_counters():
    """The membership surface itself: add starts + registers, remove
    drains through the quorum check, and the lifecycle counter family
    records each transition."""
    fleet = serving.Fleet([_StubEngine()], names=["seed0"],
                          config=serving.FleetConfig(quorum=1))
    fleet._started = True
    rep = fleet.add_replica(_StubEngine(), name="scale1")
    assert rep.name == "scale1" and len(fleet.replicas) == 2
    with pytest.raises(ValueError):
        fleet.add_replica(_StubEngine(), name="scale1")  # dup name
    eng = fleet.remove_replica("scale1", drain=True)
    assert len(fleet.replicas) == 1 and not eng._started
    with pytest.raises(serving.FleetBelowQuorum):
        fleet.remove_replica("seed0")
    with pytest.raises(KeyError):
        fleet.remove_replica("ghost")
    lc = fleet.stats._lifecycle
    assert lc["added"].value == 1 and lc["removed"].value == 1


# ------------------------------------------------ two-process kill -9 smoke
def test_two_process_kill9_exact_typed_accounting(tmp_path):
    """The CI faults-job smoke: spawn one real replica_main child,
    SIGKILL it mid-load, and demand EXACT typed accounting — every
    future resolves, submitted == sum(outcomes), one fleet span per
    request. Gated fast (<60s): brute-force searcher, 256 rows."""
    import random

    base = random.randint(42000, 55000)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    child = subprocess.Popen(
        [sys.executable, "-m", "raft_tpu.serving.replica_main",
         "--rank", "1", "--size", "2", "--base-port", str(base),
         "--family", "brute_force", "--dim", str(DIM), "--rows", "256",
         "--seed", "1", "--max-batch", "4", "--max-wait-us", "1000",
         "--peer-grace", "0.5"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        text=True)
    fleet = ep0 = None
    try:
        ready = False
        for line in child.stdout:
            if line.startswith("REPLICA_READY"):
                ready = True
                break
        assert ready, "child never printed REPLICA_READY"
        ep0 = HostP2P(rank=0, size=2, base_port=base, timeout=30,
                      peer_grace=0.5)
        proxy = RemoteReplica(ep0, peer=1, dim=DIM, name="remote1",
                              rpc_timeout_s=5.0, rpc_slack_s=0.5)
        local = Engine(build_searcher(_spec()),
                       EngineConfig(max_batch=4, max_wait_us=1000))
        sink = ListSink()
        fleet = serving.Fleet(
            [local, proxy], names=["local0", "remote1"],
            config=serving.FleetConfig(quorum=1, span_sink=sink,
                                       probe_interval_s=0.5))
        fleet.start()
        rng = np.random.default_rng(0)
        qs = [rng.standard_normal(DIM).astype(np.float32)
              for _ in range(40)]
        for q in qs[:5]:
            fleet.submit(q, K).result(timeout=30)  # real cross-process
        futs = []
        for n, q in enumerate(qs):
            futs.append(fleet.submit(q, K))
            if n == 10:
                os.kill(child.pid, signal.SIGKILL)
        for f in futs:
            exc = f.exception(timeout=30)  # resolves — ok or TYPED
            if exc is not None:
                assert isinstance(
                    exc, (serving.BatchFailed, serving.Overloaded,
                          serving.EngineStopped,
                          serving.DeadlineExceeded)), exc
        oc = _reconcile(fleet)
        assert oc["submitted"] == 45
        assert len(sink.by_kind("fleet")) == oc["submitted"]
    finally:
        if fleet is not None:
            fleet.stop(drain=False)
        if ep0 is not None:
            ep0.close()
        try:
            child.kill()
        except OSError:
            pass
        child.wait(timeout=10)


# ------------------------------------- amplified interleavings (slow tier)
class _StubIndex:
    pass


def _stub_searcher(dim=DIM):
    def search(queries, k):
        q = np.asarray(queries, np.float32)
        base = q.sum(axis=1, keepdims=True)
        d = base + np.arange(k, dtype=np.float32)[None, :]
        i = (np.abs(q).sum(axis=1, keepdims=True).astype(np.int64)
             + np.arange(k, dtype=np.int64)[None, :])
        return d.astype(np.float32), i

    return serving.Searcher(family="stub", dim=dim, index=_StubIndex(),
                            search=search)


@pytest.mark.slow
@pytest.mark.interleave
def test_partition_chaos_amplified():
    """Partition/heal racing live traffic across >= 100 amplified
    interleave seeds (stub searchers: a seed costs milliseconds of
    device time, the TCP round-trips dominate). At every seed: every
    future resolves typed, the accounting reconciles exactly, and the
    severed replica is out of the healthy count while cut."""
    from raft_tpu.testing.interleave import InterleaveAmplifier, seeds

    for seed in seeds(100):
        p0, p1 = _ports(2)
        peers = [("127.0.0.1", p0), ("127.0.0.1", p1)]
        ecfg = EngineConfig(max_batch=4, max_wait_us=200,
                            hang_timeout_s=None, persistent_cache=False,
                            flight_recorder=False)
        eng_r = Engine(_stub_searcher(), ecfg).start()
        ep1 = HostP2P(rank=1, size=2, peers=peers, timeout=10,
                      peer_grace=0.3)
        server = _ReplicaServer(eng_r, ep1, frontend=0)
        threading.Thread(target=server.run, daemon=True).start()
        ep0 = HostP2P(rank=0, size=2, peers=peers, timeout=10,
                      peer_grace=0.3)
        proxy = RemoteReplica(ep0, peer=1, dim=DIM, name="remote1",
                              rpc_timeout_s=2.0, rpc_slack_s=0.3)
        eng_l = Engine(_stub_searcher(), ecfg)
        fleet = serving.Fleet(
            [eng_l, proxy], names=["local0", "remote1"],
            config=serving.FleetConfig(quorum=1, seed=seed,
                                       retry_limit=4,
                                       backoff_base_ms=0.2,
                                       backoff_cap_ms=2.0,
                                       probe_interval_s=0.01))
        futs = []
        lock = threading.Lock()

        def submitter(ti, fleet=fleet, futs=futs, lock=lock):
            trng = np.random.default_rng(1000 + ti)
            for _ in range(10):
                q = trng.standard_normal(DIM).astype(np.float32)
                try:
                    f = fleet.submit(q, K)
                except serving.EngineStopped:
                    return
                with lock:
                    futs.append(f)

        def chaos(ep0=ep0):
            heal = faults.partition_hosts(ep0, 1)
            time.sleep(0.02)
            heal()

        with InterleaveAmplifier(seed=seed, yield_probability=0.05,
                                 path_filters=("raft_tpu/serving",)):
            fleet.start()
            threads = [threading.Thread(target=submitter, args=(ti,))
                       for ti in range(2)]
            threads.append(threading.Thread(target=chaos))
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            for f in futs:
                exc = f.exception(timeout=30)
                if exc is not None:
                    assert isinstance(
                        exc, (serving.Overloaded, serving.BatchFailed,
                              serving.EngineStopped,
                              serving.DeadlineExceeded)), (seed, exc)
            fleet.stop(drain=False)
        oc = fleet.stats.outcome_counts()
        resolved = sum(v for k, v in oc.items() if k != "submitted")
        assert oc["submitted"] == resolved, (seed, oc)
        assert oc["submitted"] == len(futs), (seed, oc)
        server._stop.set()
        eng_r.stop(drain=False)
        ep0.close()
        ep1.close()
