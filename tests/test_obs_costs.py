"""obs.costs tests: chip-peak lookup, roofline placement, the drift
ratio + C001 calibration findings, and one real (tiny) AOT compile so
the jax extraction path stays honest on this stack's jax version."""

import json

import pytest

from raft_tpu.obs import costs

pytestmark = pytest.mark.fast


def _entry(**kw):
    base = dict(name="e", family="f", flops=None, hbm_bytes=None,
                temp_bytes=None, argument_bytes=None, output_bytes=None,
                compile_s=0.0)
    base.update(kw)
    return costs.EntryCost(**base)


# ------------------------------------------------------------ chip peaks
def test_peaks_lookup_longest_substring_first():
    assert costs.peaks_for_device_kind("TPU v5p chip") is \
        costs.CHIP_PEAKS["v5p"]
    assert costs.peaks_for_device_kind("TPU v5 lite pod") is \
        costs.CHIP_PEAKS["v5 lite"]
    assert costs.peaks_for_device_kind("TPU v6e") is costs.CHIP_PEAKS["v6e"]
    assert costs.peaks_for_device_kind("cpu") is None


def test_ridge_intensity():
    p = costs.ChipPeaks(flops_per_s=100.0, hbm_bytes_per_s=10.0)
    assert p.ridge_intensity == 10.0


# -------------------------------------------------------------- roofline
def test_apply_roofline_memory_and_compute_bound():
    peaks = costs.ChipPeaks(flops_per_s=1e12, hbm_bytes_per_s=1e11)
    # AI = 1 < ridge 10: memory-bound, time = bytes / bandwidth
    e = _entry(flops=1e9, hbm_bytes=1e9)
    costs.apply_roofline(e, peaks)
    assert e.bound == "memory"
    assert e.min_time_us == pytest.approx(1e9 / 1e11 * 1e6)
    assert e.peak_utilization == pytest.approx(0.1)
    # AI = 100 > ridge: compute-bound, full MXU attainable
    e = _entry(flops=1e12, hbm_bytes=1e10)
    costs.apply_roofline(e, peaks)
    assert e.bound == "compute"
    assert e.min_time_us == pytest.approx(1e6)
    assert e.peak_utilization == 1.0


def test_apply_roofline_degrades_without_peaks_or_costs():
    e = _entry(flops=1e9, hbm_bytes=1e9)
    costs.apply_roofline(e, None)  # CPU: intensity only
    assert e.arithmetic_intensity == 1.0 and e.bound is None
    e = _entry()  # backend reported nothing
    costs.apply_roofline(e, costs.CHIP_PEAKS["v5e"])
    assert e.arithmetic_intensity is None and e.min_time_us is None


# --------------------------------------------------- drift + C001 findings
def test_drift_ratio_none_without_either_side():
    assert _entry(predicted_bytes=100).drift_ratio is None
    assert _entry(temp_bytes=100).drift_ratio is None
    assert _entry(predicted_bytes=100, temp_bytes=0).drift_ratio is None
    assert _entry(predicted_bytes=300, temp_bytes=100).drift_ratio == 3.0


def _report(entries):
    return costs.CostReport(platform="cpu", device_kind="cpu", peaks=None,
                            entries=entries, budget_bytes=1 << 30)


def test_calibration_findings_flag_both_directions():
    ok = _entry(name="a", planner="p", predicted_bytes=120, temp_bytes=100)
    over = _entry(name="b", planner="p", predicted_bytes=200,
                  temp_bytes=100)
    under = _entry(name="c", planner="p", predicted_bytes=100,
                   temp_bytes=200)
    no_planner = _entry(name="d", predicted_bytes=900, temp_bytes=100)
    fs = _report([ok, over, under, no_planner]).calibration_findings()
    assert sorted(f.qualname for f in fs) == ["b", "c"]
    for f in fs:
        assert f.rule == costs.COST_RULE
        assert f.file == costs.COST_FILE
    assert "over-predicts" in next(f for f in fs if f.qualname == "b").message
    assert "under-predicts" in next(
        f for f in fs if f.qualname == "c").message


def test_report_to_dict_schema_and_format():
    e = _entry(name="a", planner="p", predicted_bytes=150, temp_bytes=100,
               flops=1e9, hbm_bytes=1e8)
    doc = json.loads(_report([e]).to_json())
    assert doc["schema"] == "raft_tpu.perf_report/v1"
    assert doc["entries"][0]["drift_ratio"] == 1.5
    assert "planner drift 1.50x" in _report([e]).format()


def test_export_gauges_lands_series():
    from raft_tpu.obs.metrics import Registry

    reg = Registry()
    e = _entry(name="a", planner="p", predicted_bytes=150, temp_bytes=100,
               flops=5.0, hbm_bytes=7.0)
    costs.export_gauges(_report([e]), registry=reg)
    doc = reg.to_json()
    assert doc["raft_tpu_cost_flops"]["series"][0]["value"] == 5.0
    drift = doc["raft_tpu_planner_drift_ratio"]["series"][0]
    assert drift["labels"] == {"entry": "a", "planner": "p"}
    assert drift["value"] == pytest.approx(1.5)


# ----------------------------------------------------- one real compile
def test_compile_entry_extracts_real_costs():
    """One tiny matmul through the real lower/compile/cost path — pins
    the jax-version quirks (list-shaped cost_analysis, memory_analysis
    attribute names) the heavier perf_report run relies on."""
    import jax
    import jax.numpy as jnp

    def make_core():
        def core(a, b):
            return (a @ b).sum(axis=1)

        sds = (jax.ShapeDtypeStruct((64, 32), jnp.float32),
               jax.ShapeDtypeStruct((32, 16), jnp.float32))
        return core, sds, {"family": "test", "planner": "toy",
                           "predicted_bytes": 64 * 16 * 4}

    e = costs.compile_entry("toy.matmul", make_core)
    assert e.family == "test" and e.planner == "toy"
    assert e.compile_s > 0
    # XLA:CPU reports both analyses on this stack; flops at least the
    # mac count, argument bytes exactly the input sizes
    assert e.flops is not None and e.flops >= 2 * 64 * 32 * 16 * 0.5
    assert e.argument_bytes == 64 * 32 * 4 + 32 * 16 * 4
    assert e.temp_bytes is not None and e.temp_bytes >= 0


def test_normalize_cost_analysis_shapes():
    assert costs._normalize_cost_analysis(None) == {}
    assert costs._normalize_cost_analysis([]) == {}
    assert costs._normalize_cost_analysis([{"flops": 2.0}]) == {"flops": 2.0}
    assert costs._normalize_cost_analysis({"flops": 3.0}) == {"flops": 3.0}
