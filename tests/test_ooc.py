"""Out-of-core streamed builds (reference analog: host-memory datasets +
batched staging, wiki_all larger-than-memory workflow)."""


import numpy as np
import pytest

from raft_tpu import Resources, native
from raft_tpu.neighbors import brute_force, ivf_flat, ivf_pq, ooc
from raft_tpu.stats import neighborhood_recall


@pytest.fixture(scope="module")
def fbin(tmp_path_factory):
    rng = np.random.default_rng(7)
    db = rng.standard_normal((6000, 32)).astype(np.float32)
    q = rng.standard_normal((100, 32)).astype(np.float32)
    path = str(tmp_path_factory.mktemp("ooc") / "base.fbin")
    native.write_bin(path, db)
    return path, db, q


def test_sample_rows(fbin):
    path, db, _ = fbin
    s = ooc.sample_rows_from_file(path, 500, batch_rows=1000)
    assert s.shape == (500, 32)
    # every sampled row is an actual dataset row
    assert np.isin(s[:, 0], db[:, 0]).all()


@pytest.mark.slow
def test_streamed_ivf_flat_matches_recall(fbin):
    path, db, q = fbin
    _, gt = brute_force.knn(q, db, k=10, metric="sqeuclidean")
    params = ivf_flat.IndexParams(n_lists=16)
    index = ooc.build_ivf_flat_from_file(path, params, res=Resources(seed=2),
                                         batch_rows=1000)
    assert index.size == len(db)
    assert int(np.asarray(index.list_sizes).sum()) == len(db)
    _, i = ivf_flat.search(index, q, 10, ivf_flat.SearchParams(n_probes=16))
    rec = float(neighborhood_recall(np.asarray(i), np.asarray(gt)))
    assert rec >= 0.999  # all lists probed → exact


@pytest.mark.slow
def test_streamed_ivf_flat_ids_roundtrip(fbin):
    path, db, _ = fbin
    params = ivf_flat.IndexParams(n_lists=8)
    index = ooc.build_ivf_flat_from_file(path, params, res=Resources(seed=2),
                                         batch_rows=700)
    # every stored id's vector matches the dataset row
    data = np.asarray(index.list_data)
    idxs = np.asarray(index.list_indices)
    sizes = np.asarray(index.list_sizes)
    for l in range(8):
        s = int(sizes[l])
        np.testing.assert_array_equal(data[l, :s], db[idxs[l, :s]])


@pytest.mark.slow
def test_streamed_ivf_pq_recall(fbin):
    path, db, q = fbin
    _, gt = brute_force.knn(q, db, k=10, metric="sqeuclidean")
    params = ivf_pq.IndexParams(n_lists=16, pq_dim=16)
    index = ooc.build_ivf_pq_from_file(path, params, res=Resources(seed=2),
                                       batch_rows=1000)
    assert index.size == len(db)
    sp = ivf_pq.SearchParams(n_probes=16)
    _, i = ivf_pq.search(index, q, 10, sp)
    rec = float(neighborhood_recall(np.asarray(i), np.asarray(gt)))
    assert rec >= 0.7  # PQ quantization floor at full probing

    # streamed equals in-memory built from the same trainset contract:
    # encode path identical → recall within a few points
    mem = ivf_pq.build(db, ivf_pq.IndexParams(n_lists=16, pq_dim=16),
                       res=Resources(seed=2))
    _, im = ivf_pq.search(mem, q, 10, sp)
    rec_mem = float(neighborhood_recall(np.asarray(im), np.asarray(gt)))
    assert abs(rec - rec_mem) < 0.1


@pytest.mark.slow
def test_sharded_ivf_pq_from_file(fbin):
    """MNMG streamed build: per-shard ooc builds with file-absolute ids,
    SPMD search + ICI merge matches the recall of the in-memory sharded
    build (BASELINE target #4 shape)."""
    import jax

    from raft_tpu.parallel import comms as cm, sharded

    path, db, q = fbin
    comms = cm.init_comms(jax.devices(), axis="data")
    _, gt = brute_force.knn(q, db, k=10, metric="sqeuclidean")
    idx = sharded.build_ivf_pq_from_file(
        comms, path, ivf_pq.IndexParams(n_lists=8, pq_dim=16),
        res=Resources(seed=2), batch_rows=1000)
    d, i = sharded.search_ivf_pq(idx, q, 10,
                                 ivf_pq.SearchParams(n_probes=8))
    i = np.asarray(i)
    rec = float(neighborhood_recall(i, np.asarray(gt)))
    assert rec >= 0.6, f"sharded ooc ivf_pq recall {rec}"
    # ids must be valid file-absolute row ids
    assert ((i >= -1) & (i < len(db))).all()


@pytest.mark.slow
def test_sharded_ivf_flat_from_file(fbin):
    import jax

    from raft_tpu.parallel import comms as cm, sharded

    path, db, q = fbin
    comms = cm.init_comms(jax.devices(), axis="data")
    _, gt = brute_force.knn(q, db, k=10, metric="sqeuclidean")
    idx = sharded.build_ivf_flat_from_file(
        comms, path, ivf_flat.IndexParams(n_lists=8),
        res=Resources(seed=2), batch_rows=1000)
    d, i = sharded.search_ivf_flat(idx, q, 10,
                                   ivf_flat.SearchParams(n_probes=8))
    rec = float(neighborhood_recall(np.asarray(i), np.asarray(gt)))
    assert rec >= 0.999, f"sharded ooc ivf_flat recall {rec}"
