"""Ball-cover (exact landmark-pruned kNN) + epsilon-neighborhood tests
(reference: cpp/test/neighbors/ball_cover.cu, epsilon_neighborhood.cu)."""

import numpy as np
import pytest

from raft_tpu.neighbors import ball_cover, brute_force, epsilon_neighborhood
from raft_tpu.stats import neighborhood_recall


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(9)
    db = rng.standard_normal((2000, 3)).astype(np.float32)
    q = rng.standard_normal((64, 3)).astype(np.float32)
    return db, q


def test_ball_cover_exact(data):
    db, q = data
    index = ball_cover.build(db, metric="euclidean")
    d, i = ball_cover.knn(index, q, k=10)
    gt_d, gt_i = brute_force.knn(q, db, k=10, metric="euclidean")
    assert float(neighborhood_recall(np.asarray(i), np.asarray(gt_i))) >= 0.999
    np.testing.assert_allclose(np.asarray(d), np.asarray(gt_d), rtol=1e-3,
                               atol=1e-3)


def test_ball_cover_sqeuclidean_output(data):
    db, q = data
    index = ball_cover.build(db, metric="sqeuclidean")
    d, i = ball_cover.knn(index, q, k=5)
    gt_d, gt_i = brute_force.knn(q, db, k=5, metric="sqeuclidean")
    assert float(neighborhood_recall(np.asarray(i), np.asarray(gt_i))) >= 0.999
    np.testing.assert_allclose(np.asarray(d), np.asarray(gt_d), rtol=1e-3,
                               atol=1e-3)


def test_ball_cover_haversine():
    rng = np.random.default_rng(4)
    # lat ∈ [-π/2, π/2], lon ∈ [-π, π]
    db = np.stack([rng.uniform(-np.pi / 2, np.pi / 2, 500),
                   rng.uniform(-np.pi, np.pi, 500)], 1).astype(np.float32)
    q = np.stack([rng.uniform(-np.pi / 2, np.pi / 2, 20),
                  rng.uniform(-np.pi, np.pi, 20)], 1).astype(np.float32)
    index = ball_cover.build(db, metric="haversine")
    d, i = ball_cover.knn(index, q, k=5)
    gt_d, gt_i = brute_force.knn(q, db, k=5, metric="haversine")
    assert float(neighborhood_recall(np.asarray(i), np.asarray(gt_i))) >= 0.99


def test_ball_cover_validation(data):
    db, _ = data
    with pytest.raises(ValueError, match="supports"):
        ball_cover.build(db, metric="cosine")


def test_eps_neighbors(data):
    db, q = data
    eps = 1.0
    adj, deg = epsilon_neighborhood.eps_neighbors(q, db, eps)
    d = ((q[:, None, :] - db[None, :, :]) ** 2).sum(-1)
    want = d <= eps
    np.testing.assert_array_equal(np.asarray(adj), want)
    np.testing.assert_array_equal(np.asarray(deg), want.sum(1))


def test_ball_cover_eps_nn(data):
    """RBC eps_nn matches the dense epsilon_neighborhood adjacency
    (reference: ball_cover::eps_nn, ball_cover-inl.cuh:313-365)."""
    db, q = data
    eps = 1.2
    index = ball_cover.build(db, metric="euclidean")
    adj, deg = ball_cover.eps_nn(index, q, eps)
    adj = np.asarray(adj)
    ref = np.sqrt(((q[:, None, :] - db[None, :, :]) ** 2).sum(-1)) <= eps
    np.testing.assert_array_equal(adj, ref)
    np.testing.assert_array_equal(np.asarray(deg), ref.sum(1))
