"""Host async p2p tests — the UCX role (comms_t::isend/irecv/waitall,
core/comms.hpp:137-141; std_comms UCX impl detail/std_comms.hpp:211-253).
Endpoints here live in one process (threads), exactly how the reference's
send_recv self-tests exercise the channel (comms/comms_test.hpp:269-340)."""

import socket

import numpy as np
import pytest

from raft_tpu.parallel.host_p2p import HostP2P


def _ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


@pytest.fixture()
def pair():
    ports = _ports(2)
    peers = [("127.0.0.1", p) for p in ports]
    a = HostP2P(0, 2, peers=peers, timeout=30)
    b = HostP2P(1, 2, peers=peers, timeout=30)
    yield a, b
    a.close()
    b.close()


def test_isend_irecv_arrays(pair):
    a, b = pair
    x = np.arange(24, dtype=np.float32).reshape(4, 6)
    s = a.isend(x, dest=1)
    r = b.irecv(source=0)
    HostP2P.waitall([s])
    got = r.wait(30)
    assert got.dtype == np.float32
    np.testing.assert_array_equal(got, x)


def test_bytes_passthrough_and_tags(pair):
    a, b = pair
    # out-of-order tags must route to the matching irecv
    s1 = a.isend(b"tag7-payload", dest=1, tag=7)
    s2 = a.isend(b"tag3-payload", dest=1, tag=3)
    r3 = b.irecv(source=0, tag=3)
    r7 = b.irecv(source=0, tag=7)
    HostP2P.waitall([s1, s2])
    assert r3.wait(30) == b"tag3-payload"
    assert r7.wait(30) == b"tag7-payload"


def test_waitall_mixed_and_ring_exchange(pair):
    a, b = pair
    xa = np.full((8,), 1.5, np.float32)
    xb = np.full((8,), 2.5, np.float32)
    reqs = [a.isend(xa, 1), b.isend(xb, 0),
            a.irecv(source=1), b.irecv(source=0)]
    out = HostP2P.waitall(reqs, timeout=30)
    assert out[0] is None and out[1] is None  # sends carry no payload
    np.testing.assert_array_equal(out[2], xb)
    np.testing.assert_array_equal(out[3], xa)


def test_sendrecv_paired(pair):
    a, b = pair
    import threading

    res = {}

    def right():
        res["b"] = b.sendrecv(np.arange(3), dest=0, source=0)

    t = threading.Thread(target=right)
    t.start()
    res["a"] = a.sendrecv(np.arange(5), dest=1, source=1)
    t.join(30)
    np.testing.assert_array_equal(res["a"], np.arange(3))
    np.testing.assert_array_equal(res["b"], np.arange(5))


def test_same_tag_messages_keep_post_order(pair):
    """Non-overtaking: N isends with one (dest, tag) must be received by
    N irecvs in post order (the MPI/UCX ordering contract)."""
    a, b = pair
    recvs = [b.irecv(source=0, tag=1) for _ in range(16)]
    sends = [a.isend(np.array([i], np.int32), dest=1, tag=1)
             for i in range(16)]
    HostP2P.waitall(sends, timeout=30)
    got = [int(r.wait(30)[0]) for r in recvs]
    assert got == list(range(16)), got


def test_timed_out_irecv_does_not_steal_message(pair):
    """A cancelled (timed-out) irecv must not consume the message its
    retry is waiting for."""
    a, b = pair
    r1 = b.irecv(source=0, tag=5)
    with pytest.raises(TimeoutError):
        r1.wait(0.2)
    a.isend(b"late", dest=1, tag=5).wait(30)
    r2 = b.irecv(source=0, tag=5)
    assert r2.wait(30) == b"late"


def test_irecv_timeout():
    ports = _ports(1)
    ep = HostP2P(0, 1, peers=[("127.0.0.1", ports[0])], timeout=0.2)
    try:
        r = ep.irecv(source=0, tag=99)
        with pytest.raises(TimeoutError):
            r.wait(5)
    finally:
        ep.close()


def test_overlap_with_device_compute(pair):
    """The consumer pattern the facade exists for: host exchange in flight
    while device work proceeds (raft-dask's overlap of UCX traffic with
    stream compute)."""
    import jax.numpy as jnp

    a, b = pair
    big = np.random.default_rng(0).standard_normal((512, 128)).astype(
        np.float32)
    s = a.isend(big, dest=1)
    r = b.irecv(source=0)
    dev = jnp.ones((256, 256)) @ jnp.ones((256, 256))  # device compute
    out = r.wait(30)
    HostP2P.waitall([s])
    assert float(dev[0, 0]) == 256.0
    np.testing.assert_array_equal(out, big)


def test_close_fails_queued_sends_and_rejects_new_isend(monkeypatch):
    """ADVICE r2: close() must not strand queued isends — every request
    still in a sender queue fails with ConnectionError (never a hang), and
    isend after close raises instead of silently queueing.

    The sender's connect is patched to block until close() (a localhost
    connect to a dead port fails instantly with ECONNREFUSED, which would
    route requests through the poison path instead of the drain under
    test): item 1 sits in-flight inside _connect, items 2-4 stay QUEUED."""
    import raft_tpu.parallel.host_p2p as hp2p

    def blocking_connect(self, dest):
        self._closed.wait(30)
        raise ConnectionError("connect aborted by close")

    monkeypatch.setattr(hp2p.HostP2P, "_connect", blocking_connect)
    ports = _ports(2)
    peers = [("127.0.0.1", p) for p in ports]
    a = HostP2P(0, 2, peers=peers, timeout=60)
    reqs = [a.isend(b"x" * 64, dest=1) for _ in range(4)]
    assert not any(r.done() for r in reqs)  # all pending: none connected
    a.close()
    for r in reqs:
        with pytest.raises(ConnectionError):
            r.wait(10)  # bounded: close() drained the queue
    with pytest.raises(ConnectionError):
        a.isend(b"late", dest=1)


def test_close_interrupts_inflight_connect(monkeypatch):
    """A sender blocked INSIDE the TCP handshake (peer blackholes SYNs —
    dead host, dropped packets) must fail bounded at close(): _connect
    polls the non-blocking handshake in short slices that observe _closed.
    The handshake is forced to never complete (this sandbox's network
    accepts connections to ANY address instantly, so no real blackhole
    address exists here): connect_ex pends forever and the socket is
    never reported writable."""
    import time as _time

    import raft_tpu.parallel.host_p2p as hp2p

    monkeypatch.setattr(
        socket.socket, "connect_ex",
        lambda self, addr: __import__("errno").EINPROGRESS)
    monkeypatch.setattr(
        hp2p.HostP2P, "_wait_writable",
        lambda self, sock: _time.sleep(0.1) or False)
    ports = _ports(2)
    peers = [("127.0.0.1", p) for p in ports]
    a = HostP2P(0, 2, peers=peers, timeout=120)
    try:
        req = a.isend(b"x", dest=1)
        _time.sleep(0.5)  # sender dequeues and enters the handshake loop
        assert not req.done()  # genuinely stuck mid-handshake
    finally:
        a.close()
    with pytest.raises(ConnectionError):
        req.wait(10)  # bounded despite timeout=120


def test_send_failure_poisons_stream(pair):
    """ADVICE r2: after a failed send, later requests to that destination
    fail too — the (dest, tag) stream never contains a silent gap."""
    a, b = pair
    # sanity: the stream works first
    s0 = a.isend(b"ok", dest=1)
    assert b.irecv(source=0).wait(30) == b"ok"
    HostP2P.waitall([s0], timeout=30)
    # break the transport under rank 0's sender: retarget dest 1 at a
    # dead port and force reconnect by closing b's listener side
    b.close()
    # the established socket may absorb a send or two into its buffer
    # before the peer's RST lands; keep sending until one fails (bounded).
    # No reconnect ever happens: the first failure permanently poisons
    # the stream, which is exactly the contract under test.
    failed = False
    for _ in range(20):
        try:
            a.isend(b"lost", dest=1).wait(30)
        except OSError:
            failed = True
            break
    assert failed, "no send ever failed against a closed peer"
    s2 = a.isend(b"after", dest=1)
    with pytest.raises(ConnectionError, match="poisoned"):
        s2.wait(30)


def test_waitall_single_deadline():
    """ADVICE r2: waitall(requests, timeout) is one deadline for the whole
    batch, not timeout x len(requests)."""
    import time as _time

    ports = _ports(1)
    ep = HostP2P(0, 1, peers=[("127.0.0.1", ports[0])], timeout=5)
    try:
        reqs = [ep.irecv(source=0, tag=7) for _ in range(5)]
        t0 = _time.monotonic()
        with pytest.raises(TimeoutError):
            HostP2P.waitall(reqs, timeout=0.5)
        assert _time.monotonic() - t0 < 2.0  # not 5 x 0.5 + slack
    finally:
        ep.close()


def test_close_fails_pending_irecv():
    """close() must fail pending irecvs too (their message can never
    arrive), and irecv after close raises — symmetric with isend."""
    ports = _ports(1)
    ep = HostP2P(0, 1, peers=[("127.0.0.1", ports[0])], timeout=5)
    r = ep.irecv(source=0, tag=3)
    ep.close()
    with pytest.raises(ConnectionError):
        r.wait(10)  # bounded, not a hang
    with pytest.raises(ConnectionError):
        ep.irecv(source=0)


# ------------------------------------------------------- injectable clock

class _FakeClock:
    """Manually advanced monotonic clock (the fake-clock batcher idiom):
    time moves only when the test says so."""

    def __init__(self):
        import threading
        self._t = 0.0
        self._lock = threading.Lock()

    def __call__(self):
        with self._lock:
            return self._t

    def advance(self, dt):
        with self._lock:
            self._t += dt


def test_fake_clock_drives_request_deadline():
    """Every Request deadline runs on the endpoint's injected clock: a
    60s wait expires the instant synthetic time passes it (bounded real
    time), and never while synthetic time stands still."""
    import threading
    import time as _time

    ports = _ports(1)
    clk = _FakeClock()
    ep = HostP2P(0, 1, peers=[("127.0.0.1", ports[0])], timeout=30,
                 clock=clk)
    try:
        r = ep.irecv(source=0, tag=1)
        t0 = _time.monotonic()
        done = threading.Event()
        raised = []

        def waiter():
            try:
                r.wait(60.0)
            except TimeoutError as e:
                raised.append(e)
            done.set()

        th = threading.Thread(target=waiter)
        th.start()
        _time.sleep(0.2)
        assert not done.is_set()  # synthetic time has not moved
        clk.advance(61.0)
        assert done.wait(5.0), "wait() must notice the synthetic expiry"
        th.join()
        assert raised, "expired deadline must raise TimeoutError"
        assert _time.monotonic() - t0 < 5.0  # never 60 real seconds
    finally:
        ep.close()


def test_fake_clock_drives_waitall_deadline():
    """waitall's single batch deadline runs on the same injected clock
    (it borrows the first request's endpoint clock)."""
    import threading
    import time as _time

    ports = _ports(1)
    clk = _FakeClock()
    ep = HostP2P(0, 1, peers=[("127.0.0.1", ports[0])], timeout=30,
                 clock=clk)
    try:
        reqs = [ep.irecv(source=0, tag=t) for t in (1, 2, 3)]
        done = threading.Event()
        raised = []

        def waiter():
            try:
                HostP2P.waitall(reqs, timeout=10.0)
            except TimeoutError as e:
                raised.append(e)
            done.set()

        th = threading.Thread(target=waiter)
        th.start()
        _time.sleep(0.2)
        assert not done.is_set()
        clk.advance(11.0)
        assert done.wait(5.0)
        th.join()
        assert raised
    finally:
        ep.close()


# ---------------------------------------------- correlation-id RPC surface

def test_correlation_id_reserved_range_and_unique():
    """correlation_id() allocates from [2**20, 2**30) — above any user
    tag — and never repeats within a working set (the request/response
    matching contract for serving.remote)."""
    import raft_tpu.parallel.host_p2p as hp2p

    ports = _ports(1)
    ep = HostP2P(0, 1, peers=[("127.0.0.1", ports[0])], timeout=5)
    try:
        cids = [ep.correlation_id() for _ in range(4096)]
        assert all(hp2p._CORR_BASE <= c < hp2p._CORR_LIMIT for c in cids)
        assert len(set(cids)) == len(cids)
    finally:
        ep.close()


def test_correlation_id_routes_reply(pair):
    """The RPC shape: requester posts irecv on a fresh cid BEFORE the
    send; responder echoes the cid as the reply tag; the reply matches
    nothing else."""
    a, b = pair
    cid = a.correlation_id()
    decoy = a.irecv(source=1, tag=a.correlation_id())  # different cid
    reply = a.irecv(source=1, tag=cid)
    b.isend(b"the-reply", dest=0, tag=cid).wait(30)
    assert reply.wait(30) == b"the-reply"
    assert not decoy.done()  # the reply matched only its own cid
    decoy._cancelled = True


def test_discard_drops_buffered_late_reply(pair):
    """discard() is the abandon half of the RPC protocol: a late reply
    sitting unclaimed in the inbox is dropped (returns the count), and a
    fresh irecv on that cid does NOT see the stale payload."""
    import time as _time

    a, b = pair
    cid = a.correlation_id()
    b.isend(b"too-late", dest=0, tag=cid).wait(30)
    # delivery to a's inbox is async; poll until discard claims it
    deadline = _time.monotonic() + 10
    dropped = 0
    while _time.monotonic() < deadline:
        dropped = a.discard(1, cid)
        if dropped:
            break
        _time.sleep(0.01)
    assert dropped == 1
    r = a.irecv(source=1, tag=cid)
    with pytest.raises(TimeoutError):
        r.wait(0.2)  # the stale payload is gone, not re-matched


# --------------------------------------------------- graceful drain frames

def test_announce_drain_fails_pending_and_future_irecvs(pair):
    """The drain control frame fails the peer's pending irecvs with the
    typed PeerDrained — and new irecvs posted after the goodbye fail the
    same way (the message can never arrive)."""
    from raft_tpu.parallel.host_p2p import PeerDrained

    a, b = pair
    pending = b.irecv(source=0, tag=4)
    a.announce_drain(1).wait(30)
    with pytest.raises(PeerDrained):
        pending.wait(30)
    late = b.irecv(source=0, tag=5)
    with pytest.raises(PeerDrained):
        late.wait(30)


def test_drain_cleared_by_new_delivery(pair):
    """A delivery after the goodbye proves the peer came back: the
    drained verdict clears and the stream works again (the rejoin path
    serving.remote's re-admission rides)."""
    import time as _time

    from raft_tpu.parallel.host_p2p import PeerDrained

    a, b = pair
    a.announce_drain(1).wait(30)
    with pytest.raises(PeerDrained):
        b.irecv(source=0, tag=1).wait(30)
    # the drained sender keeps sending — delivery voids the verdict
    a.isend(b"back", dest=1, tag=9).wait(30)
    deadline = _time.monotonic() + 10
    got = None
    while _time.monotonic() < deadline:
        # inbox is matched before the drained verdict, so once the
        # frame lands this irecv returns it (and delivery itself
        # already cleared _drained for the NEXT irecv)
        r = b.irecv(source=0, tag=9)
        try:
            got = r.wait(0.5)
            break
        except (PeerDrained, TimeoutError):
            _time.sleep(0.01)
    assert got == b"back"


def test_drain_vs_kill_distinct_verdicts():
    """The typed accounting the fleet depends on: a graceful goodbye is
    a PROMPT typed PeerDrained; an abrupt death (kill_host — close with
    NO drain frame, a clean EOF at a frame boundary) must never forge
    one — the receiver keeps waiting its bounded timeout and the
    higher layers (RPC deadlines, the grace timer for mid-frame cuts)
    own the verdict. The two must stay distinguishable."""
    from raft_tpu.parallel.host_p2p import PeerDrained
    from raft_tpu.testing import faults

    ports = _ports(4)
    peers = [("127.0.0.1", p) for p in ports[:2]]
    a = HostP2P(0, 2, peers=peers, timeout=30, peer_grace=0.3)
    b = HostP2P(1, 2, peers=peers, timeout=30, peer_grace=0.3)
    try:
        # establish the a->b stream so the EOF is observed, then drain
        a.isend(b"hi", dest=1).wait(30)
        r = b.irecv(source=0, tag=2)
        a.announce_drain(1).wait(30)
        with pytest.raises(PeerDrained):
            r.wait(30)
    finally:
        a.close()
        b.close()
    peers2 = [("127.0.0.1", p) for p in ports[2:]]
    c = HostP2P(0, 2, peers=peers2, timeout=30, peer_grace=0.3)
    d = HostP2P(1, 2, peers=peers2, timeout=30, peer_grace=0.3)
    try:
        c.isend(b"hi", dest=1).wait(30)
        assert d.irecv(source=0).wait(30) == b"hi"
        r = d.irecv(source=0, tag=2)
        faults.kill_host(c)  # no goodbye: nothing typed may be forged
        with pytest.raises(TimeoutError) as ei:
            r.wait(1.0)  # bounded — and NOT PeerDrained
        assert not isinstance(ei.value, (PeerDrained, ConnectionError))
    finally:
        c.close()
        d.close()


# ------------------------------------------------- mid-handshake peer death

def test_peer_death_mid_handshake_fails_wait_typed(monkeypatch):
    """ISSUE 18 satellite: a peer that dies DURING the TCP handshake (SYN
    accepted, then RST before the connect completes) must fail the send's
    wait() typed and bounded — the _handshake path had no fault-injection
    twin (sever_connection only cuts established streams). The handshake
    is forced to report ECONNRESET via SO_ERROR exactly where a real
    mid-handshake RST surfaces."""
    import errno

    import raft_tpu.parallel.host_p2p as hp2p

    monkeypatch.setattr(
        socket.socket, "connect_ex",
        lambda self, addr: errno.EINPROGRESS)
    monkeypatch.setattr(
        hp2p.HostP2P, "_wait_writable", lambda self, sock: True)
    real_getsockopt = socket.socket.getsockopt

    def dying_getsockopt(self, level, optname, *args):
        if level == socket.SOL_SOCKET and optname == socket.SO_ERROR:
            return errno.ECONNRESET
        return real_getsockopt(self, level, optname, *args)

    monkeypatch.setattr(socket.socket, "getsockopt", dying_getsockopt)
    ports = _ports(2)
    peers = [("127.0.0.1", p) for p in ports]
    a = HostP2P(0, 2, peers=peers, timeout=30, retries=1,
                retry_backoff=0.01, retry_backoff_max=0.02)
    try:
        req = a.isend(b"never-lands", dest=1)
        with pytest.raises(OSError):
            req.wait(10)  # typed and bounded, not a hang
        # the failure poisons the stream like any exhausted-retries send
        with pytest.raises(ConnectionError, match="poisoned"):
            a.isend(b"after", dest=1).wait(10)
    finally:
        a.close()


def test_peer_death_mid_handshake_fails_waitall_typed(monkeypatch):
    """Same injected mid-handshake RST, via the batch path: waitall over
    a mixed batch raises the send's typed OSError within one deadline."""
    import errno

    import raft_tpu.parallel.host_p2p as hp2p

    monkeypatch.setattr(
        socket.socket, "connect_ex",
        lambda self, addr: errno.EINPROGRESS)
    monkeypatch.setattr(
        hp2p.HostP2P, "_wait_writable", lambda self, sock: True)
    real_getsockopt = socket.socket.getsockopt

    def dying_getsockopt(self, level, optname, *args):
        if level == socket.SOL_SOCKET and optname == socket.SO_ERROR:
            return errno.ECONNRESET
        return real_getsockopt(self, level, optname, *args)

    monkeypatch.setattr(socket.socket, "getsockopt", dying_getsockopt)
    ports = _ports(2)
    peers = [("127.0.0.1", p) for p in ports]
    a = HostP2P(0, 2, peers=peers, timeout=30, retries=1,
                retry_backoff=0.01, retry_backoff_max=0.02)
    try:
        reqs = [a.isend(b"x", dest=1), a.isend(b"y", dest=1)]
        with pytest.raises(OSError):
            HostP2P.waitall(reqs, timeout=10)
    finally:
        a.close()


# ------------------------------------------------- partition / heal / reset

def test_partition_refuses_typed_and_heal_restores(pair):
    """faults.partition_hosts: outbound connects to a partitioned rank
    fail typed (EHOSTUNREACH rides the cause chain into the poisoned
    stream), and heal() + reset_stream carries traffic again — the
    transport half of the fleet's re-admission story."""
    import errno

    from raft_tpu.testing import faults

    a, b = pair
    a.isend(b"pre", dest=1).wait(30)
    assert b.irecv(source=0).wait(30) == b"pre"
    heal = faults.partition_hosts(a, 1)  # one-sided: the split-brain cut
    with pytest.raises(OSError) as ei:
        a.isend(b"lost", dest=1).wait(30)
    causes, seen = [], ei.value
    while seen is not None:
        causes.append(seen)
        seen = seen.__cause__
    assert any(getattr(c, "errno", None) == errno.EHOSTUNREACH
               for c in causes), causes
    # while partitioned the stream stays poisoned even after reset: the
    # reconnect refuses again (reset_stream is not a bypass)
    a.reset_stream(1)
    with pytest.raises(OSError):
        a.isend(b"still-lost", dest=1).wait(30)
    heal()  # clears the partition AND the poison on both sides
    a.isend(b"healed", dest=1, tag=8).wait(30)
    assert b.irecv(source=0, tag=8).wait(30) == b"healed"
