"""Flight-recorder tests (docs/observability.md "Crash flight recorder").

The recorder's contract, pinned here:

- :class:`~raft_tpu.obs.spans.RingSink` stays bounded at ``capacity``
  under concurrent emitters and never loses the newest records;
- a diagnostics bundle round-trips through disk (atomic write, schema
  marker, collision-safe names) via :func:`~raft_tpu.obs.load_bundle`;
- an injected dispatch hang leaves a complete bundle behind — the hang
  batch span on the tape, a registry snapshot, ``health()`` at its
  unhealthy worst, and the effective config — both through the
  watchdog's auto-dump and through ``GET /debug/bundle``;
- auto-dumps are rate-limited so a flapping breaker can't spam disk.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from raft_tpu import serving
from raft_tpu.neighbors import ivf_flat
from raft_tpu.obs import RingSink, build_bundle, load_bundle, write_bundle
from raft_tpu.obs.diagnostics import BUNDLE_SCHEMA
from raft_tpu.obs.spans import ListSink
from raft_tpu.testing import faults

pytestmark = pytest.mark.fast

DIM = 16
K = 5


# ------------------------------------------------------------- RingSink
def test_ring_sink_bounded_and_oldest_first():
    ring = RingSink(capacity=4)
    for n in range(10):
        ring.emit({"n": n})
    assert len(ring) == 4
    assert [r["n"] for r in ring.records] == [6, 7, 8, 9]
    assert ring.emitted == 10
    assert ring.dropped == 6
    ring.clear()
    assert len(ring) == 0 and ring.records == []


def test_ring_sink_rejects_nonpositive_capacity():
    with pytest.raises(ValueError, match="capacity"):
        RingSink(capacity=0)


def test_ring_sink_tees_to_inner_and_survives_poison_inner():
    inner = ListSink()
    ring = RingSink(capacity=2, inner=inner)
    ring.emit({"a": 1})
    assert inner.records == [{"a": 1}]

    class Exploding:
        def emit(self, record):
            raise RuntimeError("inner sink down")

    ring2 = RingSink(capacity=2, inner=Exploding())
    ring2.emit({"b": 2})  # must not raise
    assert ring2.records == [{"b": 2}]


def test_ring_sink_bounded_under_concurrent_emitters():
    """4 threads x 500 emits: the tape stays exactly at capacity, the
    emitted counter loses nothing, and every surviving record is one of
    the emitted ones."""
    ring = RingSink(capacity=64)
    n_threads, per_thread = 4, 500

    def emitter(tid):
        for n in range(per_thread):
            ring.emit({"tid": tid, "n": n})

    threads = [threading.Thread(target=emitter, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert ring.emitted == n_threads * per_thread
    assert len(ring) == 64
    assert ring.dropped == n_threads * per_thread - 64
    for r in ring.records:
        assert 0 <= r["tid"] < n_threads and 0 <= r["n"] < per_thread


# ------------------------------------------------------ bundle round-trip
def test_bundle_roundtrip_and_schema_gate(tmp_path):
    from raft_tpu.obs.metrics import Registry

    reg = Registry()
    reg.counter("fr_test_total", "h").inc(3)
    doc = build_bundle("unit-test", spans=[{"kind": "x"}], registry=reg,
                       health={"status": "ok"}, config={"max_batch": 8})
    assert doc["schema"] == BUNDLE_SCHEMA
    assert doc["metrics"]["fr_test_total"]["series"][0]["value"] == 3
    path = write_bundle(str(tmp_path), doc)
    back = load_bundle(path)
    assert back["reason"] == "unit-test"
    assert back["spans"] == [{"kind": "x"}]
    # same-second second dump gets a distinct collision-suffixed name
    path2 = write_bundle(str(tmp_path), doc)
    assert path2 != path
    # a non-bundle json is refused, not half-parsed
    junk = tmp_path / "junk.json"
    junk.write_text(json.dumps({"schema": "other/v9"}))
    with pytest.raises(ValueError, match="not a diagnostics bundle"):
        load_bundle(str(junk))


def test_bundle_registry_failure_degrades_to_error_section():
    class BadRegistry:
        def to_json(self):
            raise RuntimeError("registry poisoned")

    doc = build_bundle("worst-case", registry=BadRegistry())
    assert "registry poisoned" in doc["metrics"]["error"]


# ------------------------------------------------------- engine recorder
@pytest.fixture(scope="module")
def flat_index():
    rng = np.random.default_rng(7)
    db = rng.standard_normal((1500, DIM)).astype(np.float32)
    return ivf_flat.build(db, ivf_flat.IndexParams(n_lists=16))


@pytest.fixture()
def searcher(flat_index):
    # fresh handle per test: fault injectors rebind .search on the handle
    return serving.ivf_flat_searcher(flat_index,
                                     ivf_flat.SearchParams(n_probes=8))


def _engine(s, **kw):
    kw.setdefault("max_batch", 8)
    kw.setdefault("max_wait_us", 5000)
    kw.setdefault("warm_ks", (K,))
    return serving.Engine(s, serving.EngineConfig(**kw))


def _q(rng):
    return rng.standard_normal(DIM).astype(np.float32)


def test_manual_dump_carries_all_sections(searcher, tmp_path):
    rng = np.random.default_rng(0)
    with _engine(searcher, hang_timeout_s=None,
                 diagnostics_dir=str(tmp_path)) as eng:
        eng.search(_q(rng), K)
        doc = eng.dump_diagnostics()
        assert doc["reason"] == "manual"
        assert eng.last_diagnostics is doc
        kinds = {s.get("kind") for s in doc["spans"]}
        assert "request" in kinds and "batch" in kinds
        assert "raft_tpu_serving_batches_total" in doc["metrics"] \
            or any("batch" in k for k in doc["metrics"])
        assert doc["health"]["status"] == "ok"
        assert doc["config"]["max_batch"] == 8
        assert doc["extra"]["ring_capacity"] == 512
        # the on-disk copy parses back
        back = load_bundle(doc["path"])
        assert back["reason"] == "manual"


def test_flight_recorder_tees_to_configured_sink(searcher):
    """Installing the recorder must not displace a user span sink."""
    user_sink = ListSink()
    rng = np.random.default_rng(1)
    with _engine(searcher, hang_timeout_s=None,
                 span_sink=user_sink) as eng:
        eng.search(_q(rng), K)
        eng.drain(60)
        assert any(r.get("kind") == "request" for r in user_sink.records)
        assert len(eng.dump_diagnostics()["spans"]) >= \
            len([r for r in user_sink.records])


def test_flight_recorder_disabled_dumps_empty_tape(searcher):
    rng = np.random.default_rng(2)
    with _engine(searcher, hang_timeout_s=None,
                 flight_recorder=False) as eng:
        eng.search(_q(rng), K)
        doc = eng.dump_diagnostics()
        assert doc["spans"] == [] and "extra" not in doc


def test_hang_auto_dumps_complete_bundle(searcher, tmp_path):
    """The chaos contract: an injected dispatch hang leaves a complete
    on-disk bundle behind — hang span on the tape, registry snapshot,
    unhealthy health — without anyone calling dump_diagnostics()."""
    rng = np.random.default_rng(3)
    with _engine(searcher, hang_timeout_s=1.0, breaker_cooldown_s=30.0,
                 max_wait_us=0, diagnostics_dir=str(tmp_path)) as eng:
        eng.search(_q(rng), K)
        faults.hang_next_dispatch(searcher, hang_s=3.0)
        victim = eng.submit(_q(rng), K)
        with pytest.raises(serving.BatchFailed) as ei:
            victim.result(timeout=60)
        assert ei.value.hang is True

        # the watchdog dumped right after tripping the breaker
        deadline = time.perf_counter() + 10
        while eng.last_diagnostics is None \
                and time.perf_counter() < deadline:
            time.sleep(0.02)
        doc = eng.last_diagnostics
        assert doc is not None, "watchdog never dumped"
        assert doc["reason"] == "watchdog_hang"
        hang_spans = [s for s in doc["spans"]
                      if s.get("kind") == "batch"
                      and s.get("outcome") == "hang"]
        assert hang_spans, f"no hang span on tape: {doc['spans']}"
        assert isinstance(doc["metrics"], dict) and doc["metrics"]
        assert doc["health"]["status"] == "unhealthy"
        assert doc["config"]["hang_timeout_s"] == 1.0

        # and the bundle really is on disk, loadable
        back = load_bundle(doc["path"])
        assert back["reason"] == "watchdog_hang"
        dumps = [s for s in doc["metrics"]
                 if "diagnostics_dumps" in s]
        assert dumps, "dump counter missing from snapshot"

        time.sleep(2.5)  # let the stuck dispatch thread drain its sleep


def test_auto_dump_rate_limit_swallows_flaps(searcher, tmp_path):
    rng = np.random.default_rng(4)
    with _engine(searcher, hang_timeout_s=None,
                 diagnostics_min_interval_s=3600.0,
                 diagnostics_dir=str(tmp_path)) as eng:
        eng.search(_q(rng), K)
        eng._auto_dump("breaker_open")
        first = eng.last_diagnostics
        assert first is not None and first["reason"] == "breaker_open"
        eng._auto_dump("breaker_open")  # inside the interval: swallowed
        assert eng.last_diagnostics is first
        # explicit dumps are an operator action and never rate-limited
        manual = eng.dump_diagnostics()
        assert manual is not first


def test_auto_dump_failure_is_counted(searcher, tmp_path):
    # graftcheck F003 regression: a recorder that cannot record must
    # not vanish — the failure lands in the registry it was meant to
    # snapshot
    rng = np.random.default_rng(5)
    with _engine(searcher, hang_timeout_s=None,
                 diagnostics_dir=str(tmp_path)) as eng:
        eng.search(_q(rng), K)

        def broken_dump(reason=None, **kw):
            raise RuntimeError("serializer broke")

        eng.dump_diagnostics = broken_dump
        eng._auto_dump("breaker_open")  # must not raise
        fam = eng.stats.registry.get(
            "raft_tpu_serving_diagnostics_dump_errors_total")
        assert fam is not None
        counts = {labels: child.value for labels, child in fam.collect()}
        assert counts[(eng.stats.engine_label, "breaker_open")] == 1


def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=5) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def test_debug_bundle_endpoint(searcher):
    rng = np.random.default_rng(5)
    with _engine(searcher, hang_timeout_s=None) as eng:
        eng.search(_q(rng), K)
        srv = eng.serve_metrics(port=0)
        code, body = _get(srv.url + "/debug/bundle")
        assert code == 200
        doc = json.loads(body)
        assert doc["schema"] == BUNDLE_SCHEMA
        assert doc["reason"] == "http"
        assert any(s.get("kind") == "request" for s in doc["spans"])
        assert doc["health"]["status"] == "ok"


def test_debug_bundle_404_without_bundle_fn():
    from raft_tpu.obs import MetricsServer

    with MetricsServer(port=0) as srv:
        code, body = _get(srv.url + "/debug/bundle")
        assert code == 404 and "no flight recorder" in body
